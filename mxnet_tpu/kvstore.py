"""KVStore: the data-parallel gradient-sync layer.

TPU-native counterpart of src/kvstore/** and python/mxnet/kvstore.py.
The reference has three transports behind one API (in-process reduce,
NCCL allreduce, ps-lite parameter server).  Here there is ONE collective
substrate — XLA collectives — behind the same API:

  * 'local' / 'device'  — in-process reduction across the NDArray replicas
    the caller hands in (ref: src/kvstore/kvstore_local.cc + comm.h).
  * 'xla' ('nccl' accepted as a compat alias — ref kvstore_nccl.h) —
    same API; when running under an SPMD mesh (mxnet_tpu.parallel) the
    reduction is an in-graph psum over ICI, which XLA fuses into the
    step; eagerly it falls back to the local reduce.
  * 'dist_sync' / 'dist_device_sync' / 'dist_async' — multi-process over
    DCN via jax.distributed (see mxnet_tpu.parallel.dist); push/pull map
    onto process-group allreduce.  dist_async is served by the same path
    (documented emulation: sync semantics are a superset).

set_optimizer/updater semantics (server-side optimizer when
update_on_kvstore, ref kvstore_dist_server.h) are preserved.
"""
from __future__ import annotations

import functools
import math
import pickle
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp

from .base import MXNetError
from .resilience import chaos as _chaos
from .resilience import retry as _retry
from .util import env
from .ndarray.ndarray import NDArray
from . import optimizer as opt_mod

__all__ = ["KVStore", "create"]


def _as_list(x):
    return list(x) if isinstance(x, (list, tuple)) else [x]


class KVStore:
    def __init__(self, kind: str):
        self._kind = kind
        self._store: Dict[Union[int, str], NDArray] = {}
        self._updater: Optional[Callable] = None
        self._optimizer: Optional[opt_mod.Optimizer] = None
        self._compression = None
        # MXNET_COMM_QUANT error-feedback residuals for the SPMD bucket
        # reduce, keyed by ONE live bucket-layout signature: transient
        # comm state (re-zeroed when the layout changes, not
        # checkpointed — the optimizer-side residuals are the durable
        # ones; these only span consecutive identical pushes)
        self._quant_res: Dict[tuple, tuple] = {}

    # ---- identity --------------------------------------------------------
    @property
    def type(self) -> str:
        return self._kind

    @property
    def rank(self) -> int:
        if self._kind.startswith("dist"):
            return jax.process_index()
        return 0

    @property
    def num_workers(self) -> int:
        if self._kind.startswith("dist"):
            return jax.process_count()
        return 1

    # ---- core API --------------------------------------------------------
    def init(self, key, value):
        keys, values = self._normalize(key, value)
        for k, v in zip(keys, values):
            vv = v[0] if isinstance(v, list) else v
            self._store[k] = vv.copy()

    def push(self, key, value, priority: int = 0):
        keys, values = self._normalize(key, value)
        for k, v in zip(keys, values):
            vlist = _as_list(v)
            agg = self._reduce(vlist)
            if self._kind.startswith("dist"):
                agg = self._dcn_allreduce(agg, key=k)
            elif self._check_compressible(agg) and len(vlist) > 1:
                # _check_compressible first: the loud sparse+compression
                # rejection must fire even for one replica.  The lossy
                # quantize/dequantize round-trip itself is skipped for a
                # single replica + no DCN group — nothing is
                # transmitted, so nothing may be degraded; it runs only
                # when there is an (emulated) inter-device wire
                agg = self._compress_roundtrip(k, agg)
            if self._updater is not None:
                if k not in self._store:
                    raise MXNetError(f"kvstore key {k} not initialized")
                self._updater(_key_int(k), agg, self._store[k])
            else:
                self._store[k] = agg

    def pull(self, key, out=None, priority: int = 0, ignore_sparse=True):
        from .ndarray.sparse import BaseSparseNDArray

        keys, outs = self._normalize(key, out)
        for k, o in zip(keys, outs):
            if k not in self._store:
                raise MXNetError(f"kvstore key {k} not initialized")
            src = self._store[k]
            for dst in _as_list(o):
                if isinstance(dst, BaseSparseNDArray):
                    raise MXNetError(
                        "pull with a sparse out is not supported; use "
                        "row_sparse_pull (ref: KVStoreLocal::PullImpl)")
                # ._data: the dense payload (for sparse src, .data is the
                # values block — reference naming)
                dst._data = src.as_in_context(dst.ctx)._data

    def pushpull(self, key, value, out=None, priority: int = 0):
        """Fused push+pull (ref: MXKVStorePushPullEx). Without an updater
        this is a pure allreduce — the hot path for Trainer."""
        from .ndarray.sparse import BaseSparseNDArray

        keys, values = self._normalize(key, value)
        _, outs = self._normalize(key, out if out is not None else value)
        for k, v, o in zip(keys, values, outs):
            vlist = _as_list(v)
            agg = self._reduce(vlist)
            if self._kind.startswith("dist"):
                agg = self._dcn_allreduce(agg, key=k)
            elif self._check_compressible(agg) and len(vlist) > 1:
                # see push(): sparse rejection stays loud; the lossy
                # round-trip is skipped when nothing is transmitted
                agg = self._compress_roundtrip(k, agg)
            if self._updater is not None:
                if k not in self._store:
                    raise MXNetError(f"kvstore key {k} not initialized")
                self._updater(_key_int(k), agg, self._store[k])
                agg = self._store[k]
            for dst in _as_list(o):
                if isinstance(dst, BaseSparseNDArray):
                    raise MXNetError(
                        "pushpull with a sparse out is not supported; use "
                        "push + row_sparse_pull")
                dst._data = agg.as_in_context(dst.ctx)._data

    def pushpull_fused(self, keys, values, out=None, priority: int = 0,
                       bucket_bytes: Optional[int] = None):
        """Bucketed allreduce over MANY keys: flatten the dense values
        into ~4 MB dtype-homogeneous buckets and run ONE fused
        reduce (and, on dist stores, one DCN allreduce) per bucket
        instead of one per key — the launch-overhead half of the
        EQuARX allreduce-efficiency argument (arXiv:2506.17615).

        Same out-array semantics as calling ``pushpull(k, v, out=o)``
        per key; the bucketed path additionally publishes each reduced
        value to the store (the push contract), so a later ``pull``
        observes the latest reduction just as it did under the eager
        Trainer's push+pull loop.  Per-key treatment (server-side
        updater, gradient compression with its per-key residuals,
        sparse values) transparently falls back to the per-key loop.
        ``bucket_bytes`` defaults to ``MXNET_FUSED_BUCKET_BYTES``
        (4 MiB)."""
        from .ndarray.sparse import BaseSparseNDArray

        keys = list(keys)
        vals = [_as_list(v) for v in values]
        outs = vals if out is None else [_as_list(o) for o in out]
        if len(vals) != len(keys) or len(outs) != len(keys):
            raise MXNetError("pushpull_fused: key/value/out length mismatch")
        if (self._updater is not None or self._compression is not None
                or any(isinstance(x, BaseSparseNDArray)
                       for v in vals for x in v)):
            for k, v, o in zip(keys, vals, outs):
                self.pushpull(k, v, out=o, priority=priority)
            return
        spmd = env.get_bool("MXNET_SPMD")
        if bucket_bytes is None:
            bucket_bytes = (env.get_int("MXNET_SPMD_BUCKET_BYTES")
                            if spmd else 0) or _BUCKET_BYTES
        # order-preserving greedy packing into (dtype, n_replicas)-
        # homogeneous buckets capped at bucket_bytes (always >= 1 key)
        buckets: List[List[int]] = []
        cur: List[int] = []
        cur_sig, cur_bytes = None, 0
        for pos, v in enumerate(vals):
            d = v[0].data
            sig = (str(d.dtype), len(v))
            nbytes = d.size * d.dtype.itemsize
            if cur and (sig != cur_sig or cur_bytes + nbytes > bucket_bytes):
                buckets.append(cur)
                cur, cur_bytes = [], 0
            cur.append(pos)
            cur_sig, cur_bytes = sig, cur_bytes + nbytes
        if cur:
            buckets.append(cur)
        dist = self._kind.startswith("dist")
        from .parallel import schedule as _schedule

        for bucket in buckets:
            # schedule-ledger record: one entry per LOGICAL bucket
            # reduce, before the retry loop (a one-sided transient
            # retry must not shift this rank's seq off its peers')
            d0 = vals[bucket[0]][0].data
            _schedule.record(
                "kvstore.pushpull_fused", "pushpull", str(d0.dtype),
                sum(vals[p][0].data.size * vals[p][0].data.dtype.itemsize
                    for p in bucket))
            # chaos probe + retry per bucket — the retry policy is
            # ALWAYS engaged (a transient-marked infra failure in the
            # reduce retries in production too, not only under chaos).
            # Retrying the whole bucket is safe: each attempt re-reads
            # the unmodified gradients into fresh device copies, and
            # the store/out writes happen only after the reduce
            # succeeds.
            def _attempt(b=bucket):
                if _chaos._ACTIVE:
                    _chaos.check("kvstore.pushpull")
                if not (spmd and self._bucket_allreduce_spmd(
                        b, keys, vals, outs, dist)):
                    self._bucket_allreduce(b, keys, vals, outs, dist)

            _retry.default_policy().call(_attempt,
                                         site="kvstore.pushpull_fused")

    def _bucket_allreduce_spmd(self, poss: List[int], keys, vals, outs,
                               dist: bool) -> bool:
        """MXNET_SPMD=1: reduce one bucket as ONE jit program over the
        replica mesh — the per-replica grads are zero-copy shards of a
        stacked global array, the sum with a replicated output
        constraint makes XLA emit the AllReduce (ICI in-slice, gloo/DCN
        across processes), and each replica's output shard rebinds
        zero-copy.  Local replicas and multi-process (dist) stores are
        the SAME code path here — only the mesh differs.  Returns False
        (caller runs the classic gather/DCN path) when the bucket's
        replica layout cannot form a mesh."""
        from .parallel.mesh import replica_mesh
        from .optimizer.spmd import _mesh_devices
        from .optimizer.fused import FusedUnsupported
        from jax.sharding import NamedSharding, PartitionSpec as P

        first = vals[poss[0]]
        if len(first) == 1 and not dist:
            return False  # nothing to reduce across
        local_devs = [v.ctx.jax_device for v in first]
        try:
            mesh = replica_mesh(_mesh_devices(local_devs, dist))
        except (MXNetError, FusedUnsupported):
            return False
        for p in poss[1:]:
            if [v.ctx.jax_device for v in vals[p]] != local_devs:
                return False  # replica->device layout differs per key
        nrep = mesh.size("dp")
        shapes = tuple(tuple(vals[p][0].shape) for p in poss)
        args = []
        for p in poss:
            shp = tuple(vals[p][0].shape)
            sh = NamedSharding(mesh.mesh, P("dp", *([None] * len(shp))))
            shards = []
            for v in vals[p]:
                d = v.data
                if list(d.devices()) != [v.ctx.jax_device]:
                    # same normalization as the classic path: a buffer
                    # that drifted off its ctx device must move before
                    # it can shard the global array
                    d = jax.device_put(d, v.ctx.jax_device)
                shards.append(d[None])
            args.append(jax.make_array_from_single_device_arrays(
                (nrep,) + shp, sh, shards))
        import numpy as np
        from .optimizer import comm as _comm

        q = _comm.config()
        sizes = tuple(int(np.prod(s)) if s else 1 for s in shapes)
        quant = q.applies(sum(sizes))
        if quant:
            # encode each key's per-replica rows (+ residual), exchange
            # 1-byte codes, sum the dequantized rows locally — same
            # error-feedback scheme as the optimizer-side buckets
            from .parallel.spmd import _global_put
            qsig = (tuple(keys[p] for p in poss), shapes, nrep,
                    q.mode, q.ef)
            res = self._quant_res.get(qsig)
            if res is None:
                row_sh = NamedSharding(mesh.mesh, P("dp", None))
                res = tuple(
                    _global_put(np.zeros((nrep, n), np.float32),
                                row_sh) for n in sizes)
            out_g, new_res = _mesh_reduce_quant(
                mesh.mesh, shapes, q.mode, q.ef)(args, res)
            # one live layout: gradients push in a stable bucket order,
            # so a signature change means the layout changed for good
            self._quant_res = {qsig: new_res}
        else:
            out_g = _mesh_reduce(mesh.mesh, shapes)(*args)
        from .telemetry import tracing as _tracing
        _snk = _tracing._SINK
        if _tracing._ENABLED or _snk is not None:
            payload = sum(a.nbytes // nrep for a in args)
            enc = q.mode if quant else "raw"
            wire = sum(_comm.wire_nbytes(n, nrep, q.mode)
                       for n in sizes) if quant else payload
            if _tracing._ENABLED:
                from .telemetry import instruments as _ins

                _ins.collective_bytes_total("all-reduce",
                                            "dp").inc(payload)
                _ins.collective_wire_bytes_total("all-reduce", "dp",
                                                 enc).inc(wire)
            if _snk is not None:  # mxprof flight recorder
                _snk.on_bytes("all-reduce", "dp", payload)
                _ob = getattr(_snk, "on_wire_bytes", None)
                if _ob is not None:
                    _ob("all-reduce", "dp", enc, wire)
        for p, og in zip(poss, out_g):
            per_dev = {s.device: s.data for s in og.addressable_shards}
            ctx0 = vals[p][0].ctx
            agg = NDArray(per_dev[ctx0.jax_device], ctx=ctx0)
            self._store[keys[p]] = agg  # push contract: publish latest
            for dst in _as_list(outs[p]):
                d = per_dev.get(dst.ctx.jax_device)
                dst._data = d if d is not None \
                    else agg.as_in_context(dst.ctx)._data
        return True

    def _bucket_allreduce(self, poss: List[int], keys, vals, outs,
                          dist: bool):
        """Reduce one bucket of keys: concat per-replica flats, one
        balanced-tree sum (+ one DCN allreduce when dist), split back."""
        first = vals[poss[0]][0]
        nrep = len(vals[poss[0]])
        dev = first.ctx.jax_device
        shapes = tuple(tuple(vals[p][0].shape) for p in poss)
        parts = []
        for r in range(nrep):
            for p in poss:
                d = vals[p][r].data
                if list(d.devices()) != [dev]:
                    d = jax.device_put(d, dev)
                parts.append(d)
        if dist:
            flat = _bucket_concat_sum(nrep, len(poss))(*parts)
            flat = self._dcn_allreduce(NDArray(flat, ctx=first.ctx)).data
            segs = _bucket_split(shapes)(flat)
        else:
            segs = _bucket_sum_split(nrep, shapes)(*parts)
        for p, seg in zip(poss, segs):
            agg = NDArray(seg, ctx=first.ctx)
            self._store[keys[p]] = agg  # push contract: publish latest
            for dst in _as_list(outs[p]):
                dst._data = agg.as_in_context(dst.ctx)._data

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """ref: kvstore row_sparse_pull — pull only the requested rows.

        When `out` is a RowSparseNDArray the result is a real sparse pull:
        its indices become the (sorted, deduplicated) row_ids and only
        those rows carry values. Dense `out` gets the row-gathered dense
        emulation."""
        from .ndarray.sparse import RowSparseNDArray

        if row_ids is None:
            return self.pull(key, out, priority)
        keys, outs = self._normalize(key, out)
        _, rid_groups = self._normalize(key, row_ids)
        for k, o, rid_group in zip(keys, outs, rid_groups):
            if k not in self._store:
                raise MXNetError(f"kvstore key {k} not initialized")
            src = self._store[k]
            for dst, rid in zip(_as_list(o), _as_list(rid_group)):
                uniq = jnp.unique(rid._data.astype(jnp.int32))
                rows = jnp.take(src._data, uniq, axis=0)
                full = jnp.zeros(src.shape,
                                 src._data.dtype).at[uniq].set(rows)
                dev = dst.ctx.jax_device
                dst._data = jax.device_put(full, dev)
                if isinstance(dst, RowSparseNDArray):
                    dst._aux = {"indices": jax.device_put(uniq, dev)}

    # ---- optimizer hookup -----------------------------------------------
    def set_optimizer(self, optimizer: opt_mod.Optimizer):
        self._optimizer = optimizer
        self._updater = opt_mod.get_updater(optimizer)

    def set_updater(self, updater: Callable):
        self._updater = updater

    def set_gradient_compression(self, compression_params: dict):
        """2-bit gradient compression on the DCN (dist) push path
        (ref: GradientCompression, gradient_compression.cc): quantize to
        {0, ±threshold} with residual accumulation, 4 elements/byte on
        the wire.  Unknown types raise.  The ICI/SPMD path keeps
        uncompressed in-graph collectives by design."""
        from . import kvstore_compression

        if self._kind == "local":
            # reference parity: KVStoreLocal rejects compression; device/
            # dist stores accept it
            raise MXNetError(
                "gradient compression is not supported on 'local' "
                "kvstore (ref: KVStoreLocal::SetGradientCompression)")
        self._compression = kvstore_compression.create(compression_params)

    def save_optimizer_states(self, fname: str, dump_optimizer=False):
        if self._updater is None:
            raise MXNetError("no optimizer set on kvstore")
        with open(fname, "wb") as f:
            f.write(self._updater.get_states(dump_optimizer))

    def load_optimizer_states(self, fname: str):
        if self._updater is None:
            raise MXNetError("no optimizer set on kvstore")
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())

    def barrier(self):
        if self._kind.startswith("dist"):
            from .parallel import dist

            dist.barrier()

    # ---- internals -------------------------------------------------------
    def _reduce(self, vals: List[NDArray]) -> NDArray:
        """Local reduction across device replicas (ref: comm.h CommDevice;
        row_sparse inputs reduce to a row_sparse with merged indices, like
        the reference's sparse CommCPU path).  Dense reduction is ONE
        jitted balanced-tree sum, not a sequential add chain."""
        from .ndarray.sparse import RowSparseNDArray

        if len(vals) == 1:
            return vals[0].copy()
        dev = vals[0].ctx.jax_device
        parts = []
        for v in vals:
            d = v._data if isinstance(v, RowSparseNDArray) else v.data
            if list(d.devices()) != [dev]:
                d = jax.device_put(d, dev)
            parts.append(d)
        acc = _tree_sum(len(parts))(*parts)
        if all(isinstance(v, RowSparseNDArray) for v in vals):
            merged = jnp.sort(jnp.unique(jnp.concatenate(
                [jax.device_put(v._aux["indices"], dev) for v in vals])))
            return RowSparseNDArray(acc, {"indices": merged},
                                    ctx=vals[0].ctx)
        return NDArray(acc, ctx=vals[0].ctx)

    def _compress_nd(self, key, val: NDArray):
        """Quantize one dense NDArray -> (packed codes, shape)."""
        import numpy as np

        return self._compression.compress(
            key, np.asarray(jax.device_get(val.data)))

    def _compress_roundtrip(self, key, val: NDArray) -> NDArray:
        """Quantize+dequantize on a device-style store — the wire effect
        of 2-bit compression without a wire (ref: device-kvstore
        inter-GPU compression)."""
        packed, shape = self._compress_nd(key, val)
        return NDArray(jnp.asarray(
            self._compression.decompress(packed, shape)), ctx=val.ctx)

    def _check_compressible(self, val) -> bool:
        from .ndarray.sparse import BaseSparseNDArray

        if self._compression is None:
            return False
        if isinstance(val, BaseSparseNDArray):
            # reference parity: row_sparse + compression fails loud, it
            # never silently sends full-size gradients
            raise MXNetError(
                "gradient compression does not support sparse gradients "
                "(ref: GradientCompression row_sparse check)")
        return True

    def _dcn_allreduce(self, val: NDArray, key=None) -> NDArray:
        from .parallel import dist

        if key is not None and self._check_compressible(val):
            packed, shape = self._compress_nd(key, val)
            gathered = dist.allgather_np(packed)
            total = sum(self._compression.decompress(g, shape)
                        for g in gathered)
            return NDArray(jnp.asarray(total), ctx=val.ctx)
        return dist.allreduce_nd(val)

    def _normalize(self, key, value):
        keys = _as_list(key)
        if value is None:
            return keys, [None] * len(keys)
        if len(keys) == 1:
            return keys, [value]
        vals = _as_list(value)
        if len(vals) != len(keys):
            # grouped: values per key are lists
            raise MXNetError("key/value length mismatch")
        return keys, vals

    def __repr__(self):
        return f"KVStore(type={self._kind}, keys={len(self._store)})"


def _key_int(k):
    try:
        return int(k)
    except (TypeError, ValueError):
        return abs(hash(k)) % (2 ** 31)


def _balanced_sum(xs):
    """Pairwise (balanced-tree) sum of a list of same-shaped arrays."""
    xs = list(xs)
    while len(xs) > 1:
        nxt = [xs[i] + xs[i + 1] for i in range(0, len(xs) - 1, 2)]
        if len(xs) % 2:
            nxt.append(xs[-1])
        xs = nxt
    return xs[0]


@functools.lru_cache(maxsize=None)
def _tree_sum(n: int):
    """One fused XLA program summing n same-shaped arrays pairwise."""
    return jax.jit(lambda *xs: _balanced_sum(xs))


# ---- gradient bucketing (pushpull_fused) ---------------------------------
#
# One XLA program per bucket signature: variadic inputs arrive replica-
# major ([r0k0, r0k1, ..., r1k0, ...]); each replica's segments are
# flattened and concatenated, the replica flats are tree-summed, and the
# reduced flat is sliced back into per-key shapes.  jax.jit retraces per
# dtype/device automatically, so the lru key is structure only.

_BUCKET_BYTES = env.get_int("MXNET_FUSED_BUCKET_BYTES")


def _flat_concat(seg):
    fl = [x.reshape(-1) for x in seg]
    return fl[0] if len(fl) == 1 else jnp.concatenate(fl)


def _split_segments(flat, shapes):
    segs, off = [], 0
    for s in shapes:
        size = math.prod(s) if s else 1
        segs.append(flat[off:off + size].reshape(s))
        off += size
    return tuple(segs)


@functools.lru_cache(maxsize=None)
def _bucket_sum_split(nrep: int, shapes: tuple):
    """concat + replica tree-sum + split, fused into one program (the
    single-dispatch path for non-dist stores)."""
    nk = len(shapes)

    def f(*parts):
        flats = [_flat_concat(parts[r * nk:(r + 1) * nk])
                 for r in range(nrep)]
        return _split_segments(_balanced_sum(flats), shapes)

    return jax.jit(f)


@functools.lru_cache(maxsize=None)
def _bucket_concat_sum(nrep: int, nk: int):
    """concat + replica tree-sum -> one flat bucket (the DCN allreduce
    payload for dist stores)."""

    def f(*parts):
        return _balanced_sum([_flat_concat(parts[r * nk:(r + 1) * nk])
                              for r in range(nrep)])

    return jax.jit(f)


@functools.lru_cache(maxsize=None)
def _bucket_split(shapes: tuple):
    return jax.jit(lambda flat: _split_segments(flat, shapes))


@functools.lru_cache(maxsize=None)
def _mesh_reduce(mesh, shapes: tuple):
    """One program reducing a bucket of stacked [n_replica, ...] global
    arrays over the mesh's dp axis, outputs replicated (XLA emits the
    AllReduce; jax.Mesh is hashable, so the lru key is exact)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    repl = NamedSharding(mesh, P())

    def f(*stacks):
        return tuple(
            jax.lax.with_sharding_constraint(jnp.sum(s, axis=0), repl)
            for s in stacks)

    return jax.jit(f)


@functools.lru_cache(maxsize=None)
def _mesh_reduce_quant(mesh, shapes: tuple, mode: str, ef: bool):
    """MXNET_COMM_QUANT variant of :func:`_mesh_reduce`: each stacked
    [n_replica, ...] gradient is flattened to per-replica rows, rows are
    encoded to 1-byte codes with per-block scales (plus the carried
    error-feedback residual), the CODES are what the mesh exchanges,
    and every replica sums the dequantized rows locally — identical
    inputs on every shard, so outputs stay bit-identical across
    replicas.  Returns ``(reduced, new_residuals)``."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from .optimizer import comm as _comm

    repl = NamedSharding(mesh, P())
    row_sh = NamedSharding(mesh, P("dp", None))
    csn = jax.lax.with_sharding_constraint
    f32 = jnp.float32

    def f(stacks, res):
        outs, new_res = [], []
        for s, r, shp in zip(stacks, res, shapes):
            dt = s.dtype
            rows = csn(s.reshape(s.shape[0], -1), row_sh).astype(f32)
            acc = rows + r if ef else rows
            codes, scale = _comm.encode(acc, mode)
            new_res.append(
                csn(acc - _comm.decode(codes, scale), row_sh)
                if ef else csn(jnp.zeros_like(acc), row_sh))
            codes_r = csn(codes, repl)       # the 1-byte exchange
            scale_r = csn(scale, repl)
            red = jnp.sum(_comm.decode(codes_r, scale_r), axis=0)
            outs.append(csn(red, repl).reshape(shp).astype(dt))
        return tuple(outs), tuple(new_res)

    return jax.jit(f)


_VALID = {"local", "device", "xla", "nccl", "dist", "dist_sync", "dist_async",
          "dist_device_sync"}


_ASYNC_WARNED = [False]


def create(name: str = "local") -> KVStore:
    """ref: kvstore.create / KVStore::Create factory."""
    if name not in _VALID:
        raise MXNetError(f"unknown kvstore type {name!r}; valid: {sorted(_VALID)}")
    if name == "nccl":
        name = "xla"  # compat alias: the ICI collective store
    if name == "dist_async" and not _ASYNC_WARNED[0]:
        # one-time, loud: the staleness semantics a dist_async user
        # tuned for (hogwild-style non-blocking pushes) do not exist on
        # this backend — updates are synchronous collectives (see
        # docs/distributed.md, SURVEY.md §7 hard-part 6)
        import warnings

        warnings.warn(
            "kvstore 'dist_async' is emulated as 'dist_sync' on the TPU "
            "backend: pushes are synchronous XLA collectives, so there "
            "is no gradient staleness. Convergence behavior tuned for "
            "async PS training may differ.", UserWarning, stacklevel=2)
        _ASYNC_WARNED[0] = True
    return KVStore(name)
