"""mxtriage — the "why" layer on top of mxprof.

mxprof (PR 10) and mxhealth (PR 11) say *that* a step is slow or a
nightly regressed; mxtriage says *why*, three ways:

  * **On-demand deep capture** (:mod:`.capture`): one bounded-window,
    admission-gated ``jax.profiler`` capture API —
    ``deep_capture(steps=N | seconds=S)`` — invocable from training
    (step-boundary window), serving (``POST /profilez``), the shell
    (``kill -USR1``), and a firing alert rule
    (``action="deep_capture"``, rate-limited).  Artifacts are indexed
    beside the mxprof dump with the triggering rule/step recorded.
    The legacy manual bracket (``profiler.start_xla_trace``) and
    ``tools/profile_bench.py`` are refolded onto this path.
  * **Compile provenance** (:mod:`.provenance`): every compile-cache
    miss records which signature component changed vs the nearest
    prior compile at the same site (avals / statics / donation /
    program / env), into ``mx_compile_reason_total{site,component}``
    and the mxprof compile-event stream.
  * **Regression attribution** (:mod:`.attribution`): diff the mxprof
    aggregates embedded in fresh-vs-baseline bench artifacts into a
    ranked ``suspects`` list — what ``tools/perf_compare.py`` emits
    when a lane fails.

See docs/observability.md ("Deep capture" and "Why did it recompile /
why did it regress").
"""
from __future__ import annotations

import signal
import threading
from typing import Optional

from . import attribution, capture, provenance
from .capture import CaptureBusy, manager

__all__ = [
    "deep_capture", "start_manual", "stop_manual",
    "trigger_from_alert", "active", "index", "install_sigusr1",
    "CaptureBusy", "manager",
    "attribution", "capture", "provenance",
]


def deep_capture(steps: Optional[int] = None,
                 seconds: Optional[float] = None,
                 trigger: str = "manual",
                 rule: Optional[str] = None,
                 severity: Optional[str] = None,
                 block: bool = True,
                 timeout: Optional[float] = None) -> Optional[dict]:
    """One bounded deep capture through the process manager; see
    :meth:`.capture.CaptureManager.deep_capture`."""
    return manager().deep_capture(steps=steps, seconds=seconds,
                                  trigger=trigger, rule=rule,
                                  severity=severity, block=block,
                                  timeout=timeout)


def start_manual(logdir: Optional[str] = None) -> str:
    """Open-ended capture holding the admission slot until
    :func:`stop_manual` (what ``profiler.start_xla_trace`` calls)."""
    return manager().start_manual(logdir)


def stop_manual() -> Optional[str]:
    return manager().stop_manual()


def trigger_from_alert(rule: str, severity: Optional[str] = None,
                       value=None) -> str:
    """Rate-limited, non-blocking capture trigger for
    ``action="deep_capture"`` alert rules."""
    return manager().trigger_from_alert(rule, severity=severity,
                                        value=value)


def active() -> Optional[dict]:
    return manager().active()


def index() -> list:
    """The capture index (newest last)."""
    return manager().index()


_sig_lock = threading.Lock()
_SIG_INSTALLED = False


def _on_sigusr1(signum, frame):  # pragma: no cover — exercised via kill
    # same discipline as mxprof's SIGUSR2: NEVER work inline in the
    # handler (the interrupted frame may hold the very locks the
    # capture path needs) — a daemon thread runs the capture
    def run():
        try:
            deep_capture(trigger="sigusr1", block=True)
        except Exception:  # noqa: BLE001 — incl. CaptureBusy: signal is advisory
            pass

    threading.Thread(target=run, name="mxtriage-sigusr1",
                     daemon=True).start()


def install_sigusr1() -> bool:
    """Install the SIGUSR1 deep-capture handler (main thread only,
    best effort).  Returns whether the handler is installed."""
    global _SIG_INSTALLED
    with _sig_lock:
        if _SIG_INSTALLED:
            return True
        try:
            signal.signal(signal.SIGUSR1, _on_sigusr1)
        except (ValueError, OSError, AttributeError):
            return False  # non-main thread / platform without SIGUSR1
        _SIG_INSTALLED = True
        return True
