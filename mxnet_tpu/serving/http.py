"""Stdlib HTTP front end over InferenceServer (http.server, JSON body).

Deliberately dependency-free: the batching, backpressure, and deadline
machinery live in InferenceServer — this layer only maps HTTP to it,
including the status codes the backpressure contract promises
(503 ServerOverloaded / 504 DeadlineExceeded / 503 after shutdown /
404 unknown model or version).

    POST /v1/models/<name>:predict
    POST /v1/models/<name>/versions/<int>:predict
         body: {"inputs": [<nested lists>, ...],
                "seed": 0, "timeout_ms": 250}      (seed/timeout opt.)
         resp: {"outputs": <model's documented structure>}
               (arrays as nested lists; namedtuples/dicts as objects)
    GET  /v1/models    -> {"models": {name: [versions]}}
    GET  /v1/metrics   -> the InferenceServer.metrics() snapshot
    GET  /metrics      -> Prometheus text exposition (the whole
                          process's telemetry registry: request latency
                          histograms, AOT-compile counters, ...)
    GET  /healthz      -> 200 {"status": "serving"} while accepting,
                          503 {"status": "draining"} once shutdown
                          begins (drain-aware: LBs stop routing here
                          while accepted work completes)
    GET  /statusz      -> one human-readable page: build info, uptime,
                          per-model serving counters, mxprof snapshot
                          aggregates, the mxgoodput ratio/badput line,
                          and the currently-firing alerts
                          (telemetry.alerts.default_engine, ticked at
                          render time).  Drain-aware like /healthz:
                          the status code flips to 503 while draining
                          but the page still renders.
    POST /profilez     -> run one mxtriage deep capture and return its
                          meta (body: {"seconds": S} or {"steps": N},
                          both optional — default MXNET_TRIAGE_SECONDS).
                          Admission-gated: 409 while another capture is
                          in flight (captures never stack); drain-aware:
                          503 once shutdown begins.

Use `serve_http(server, port=0)` for an ephemeral port; the returned
`http.server.ThreadingHTTPServer` exposes `server_address` and is torn
down with `.shutdown()`.
"""
from __future__ import annotations

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..telemetry import metrics as _tmetrics
from . import ServingError

__all__ = ["serve_http"]

_PREDICT = re.compile(
    r"^/v1/models/(?P<name>[^/:]+)"
    r"(?:/versions/(?P<version>\d+))?:predict$")


def _jsonable(out):
    """Model outputs -> JSON: NDArray/device arrays to nested lists,
    namedtuples to objects (their field names survive the deploy
    round-trip, so the HTTP surface keeps them too)."""
    if isinstance(out, dict):
        return {k: _jsonable(v) for k, v in out.items()}
    if isinstance(out, tuple) and hasattr(out, "_fields"):
        return {f: _jsonable(v) for f, v in zip(out._fields, out)}
    if isinstance(out, (tuple, list)):
        return [_jsonable(v) for v in out]
    if hasattr(out, "asnumpy"):
        return out.asnumpy().tolist()
    return out


def _render_statusz(server) -> str:
    """The /statusz page body: everything an operator asks first, one
    plain-text screen — no JS, no scrape stack, survives a pager.
    Every block degrades to a stub rather than failing the render."""
    import time

    from ..telemetry import alerts as _alerts
    from ..telemetry import instruments as _ins
    from ..telemetry import mxgoodput as _mxgoodput
    from ..telemetry import mxhealth as _mxhealth
    from ..telemetry import mxprof as _mxprof

    lines = ["mxnet_tpu statusz", "================="]
    try:
        _ins.refresh_process_gauges()
        child = _ins.build_info()
        # the child's identity is its label values; recover them from
        # the family for display
        fam = _ins._family("mx_build_info")
        labels = next((dict(zip(fam.labelnames, v))
                       for v, c in fam.children() if c is child), {})
        lines.append("build:   " + ", ".join(
            f"{k}={v}" for k, v in labels.items()))
        lines.append(
            f"uptime:  {_ins._child('mx_process_uptime_seconds').value:.0f}s"
            f"   rss: {_ins._child('mx_process_rss_bytes').value / 2**20:.0f}MB")
    except Exception:  # noqa: BLE001 — statusz must always render
        lines.append("build:   (unavailable)")
    state = "DRAINING" if server.draining else "serving"
    snap = server.metrics()
    lines.append(f"state:   {state}   pending {snap['pending']}/"
                 f"{snap['max_queue']}")
    lines.append("")
    lines.append("models:")
    for m in snap["models"]:
        lines.append(
            f"  {m['model']} v{m['version']}: req {m['requests']} "
            f"ok {m['completed']} fail {m['failed']} "
            f"shed {m['rejected'] + m['breaker_rejected']} "
            f"p99 {m['p99_latency_ms'] or '-'}ms "
            f"qdepth {m['queue_depth']}")
    if not snap["models"]:
        lines.append("  (none)")
    lines.append("")
    try:
        if _mxprof.enabled():
            s = _mxprof.snapshot(live_hbm=False,
                                 include_records=False)["summary"]
            lines.append(
                f"mxprof:  steps {s.get('steps_recorded', 0)} "
                f"mean-step {s.get('wall_s_mean', '-')}s "
                f"verdicts {s.get('verdicts', {})} "
                f"mfu {s.get('mfu_mean', '-')}")
        else:
            lines.append("mxprof:  (recorder not attached)")
    except Exception:  # noqa: BLE001
        lines.append("mxprof:  (unavailable)")
    try:
        if _mxhealth.enabled():
            # flush_timeout=0: render what is already fetched — the
            # page must not stall behind a wedged device sync
            r = _mxhealth.monitor().report(flush_timeout=0.0)
            lines.append(
                f"health:  {r['verdict']} — steps {r['steps_observed']} "
                f"nonfinite {r['nonfinite_steps']} "
                f"skipped {r['skipped_steps']} "
                f"events {len(r['events'])}")
        else:
            lines.append("health:  (mxhealth not enabled)")
    except Exception:  # noqa: BLE001
        lines.append("health:  (unavailable)")
    try:
        if _mxgoodput.enabled():
            g = _mxgoodput.snapshot()
            top = sorted(((c, s) for c, s in g["badput_s"].items()
                          if s > 0), key=lambda kv: -kv[1])[:3]
            bad = ", ".join(f"{c} {s:.1f}s" for c, s in top) or "none"
            lines.append(
                f"goodput: {g['goodput_ratio']:.3f} over "
                f"{g['wall_s']:.0f}s wall — badput: {bad}; "
                f"unattributed {g['unattributed_s']:.1f}s")
        else:
            lines.append("goodput: (mxgoodput not enabled)")
    except Exception:  # noqa: BLE001
        lines.append("goodput: (unavailable)")
    lines.append("")
    lines.append("alerts:")
    try:
        eng = _alerts.default_engine()
        eng.tick()  # render-time evaluation: the page never shows a
        # stale verdict just because the background ticker is off
        firing = eng.firing()
        for a in firing:
            lines.append(f"  FIRING [{a['severity']}] {a['name']}: "
                         f"{a.get('description', '')} "
                         f"(value {a.get('value')})")
        if not firing:
            lines.append("  (none firing)")
    except Exception:  # noqa: BLE001
        lines.append("  (engine unavailable)")
    try:
        from ..telemetry import mxblackbox as _bb

        if _bb.enabled():
            evs = _bb.recent(3)
            last = ", ".join(f"{e.get('category')}:{e.get('msg')}"
                             for e in evs) or "none"
            line = f"blackbox: {len(_bb.journal())} events — {last}"
            inc = _bb.last_incident()
            if inc is not None:
                ff = inc.get("first_failure") or {}
                line += (f"; last incident {inc.get('incident_id')} "
                         f"(rank {ff.get('rank')} "
                         f"{ff.get('category')})")
            lines.append(line)
        else:
            lines.append("blackbox: (mxblackbox not enabled)")
    except Exception:  # noqa: BLE001
        lines.append("blackbox: (unavailable)")
    lines.append("")
    lines.append(f"rendered {time.strftime('%Y-%m-%d %H:%M:%S')}")
    return "\n".join(lines) + "\n"


def _make_handler(server):
    import numpy as np

    class Handler(BaseHTTPRequestHandler):
        # request logging goes through metrics, not stderr spam
        def log_message(self, fmt, *args):  # noqa: D102
            pass

        def _send_text(self, status: int, text: str, content_type: str):
            body = text.encode("utf-8")
            self.send_response(status)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _send(self, status: int, payload: dict):
            self._send_text(status, json.dumps(payload),
                            "application/json")

        def _profilez(self):
            """POST /profilez: one mxtriage deep capture, blocking
            until the bounded window closes; returns its meta."""
            from ..telemetry import mxtriage

            if server.draining:
                # drain-aware: a terminating process must not start a
                # multi-second profiler session it may not finish
                return self._send(503, {"error": "draining"})
            try:
                n = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(n) or b"{}") \
                    if n else {}
                steps = req.get("steps")
                seconds = req.get("seconds")
                meta = mxtriage.deep_capture(
                    steps=int(steps) if steps is not None else None,
                    seconds=float(seconds) if seconds is not None
                    else None,
                    trigger="http", block=True)
                if meta is None:
                    return self._send(504, {
                        "error": "capture did not complete in time"})
                status = 200 if meta.get("status") != "error" else 500
                return self._send(status, {"capture": meta})
            except mxtriage.CaptureBusy as e:
                # admission gate: captures never stack
                return self._send(409, {"error": str(e)})
            except Exception as e:  # noqa: BLE001 — HTTP boundary
                return self._send(400, {"error": str(e)})

        def do_GET(self):  # noqa: N802 — http.server API
            if self.path == "/metrics":
                # standard scrape target: the process-wide registry in
                # Prometheus text format 0.0.4
                return self._send_text(
                    200, _tmetrics.get_registry().to_prometheus(),
                    "text/plain; version=0.0.4; charset=utf-8")
            if self.path == "/healthz":
                if server.draining:
                    return self._send(503, {"status": "draining"})
                return self._send(200, {"status": "serving"})
            if self.path == "/statusz":
                # drain-aware like /healthz (an LB or a human can read
                # the state off the code), but the page still renders
                # so the operator sees WHAT is draining
                return self._send_text(
                    503 if server.draining else 200,
                    _render_statusz(server),
                    "text/plain; charset=utf-8")
            if self.path == "/v1/metrics":
                return self._send(200, server.metrics())
            if self.path == "/v1/models":
                return self._send(
                    200, {"models": server.repository.models()})
            return self._send(404, {"error": f"no route {self.path}"})

        def do_POST(self):  # noqa: N802 — http.server API
            if self.path == "/profilez":
                return self._profilez()
            m = _PREDICT.match(self.path)
            if not m:
                return self._send(404, {"error": f"no route {self.path}"})
            try:
                n = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(n) or b"{}")
                name = m.group("name")
                version = m.group("version")
                entry = server.repository.get(
                    name, int(version) if version else None)
                # admission probe BEFORE input_specs(): specs lazily
                # import the artifact, and shedding (503) must never
                # wait behind a cold model's multi-second import
                server.check_admission(entry)
                specs = entry.input_specs()
                raw = req.get("inputs")
                if not isinstance(raw, list) or len(raw) != len(specs):
                    return self._send(400, {
                        "error": f"body.inputs must be a list of "
                                 f"{len(specs)} arrays"})
                xs = [np.asarray(v, dtype=w["dtype"])
                      for v, w in zip(raw, specs)]
                # pin the version we cast against: "latest" could move
                # under a concurrent repo.add between here and infer
                out = server.infer(
                    name, xs, version=entry.version,
                    seed=int(req.get("seed", 0)),
                    timeout_ms=req.get("timeout_ms"))
                return self._send(200, {"outputs": _jsonable(out)})
            except ServingError as e:
                return self._send(e.status, {"error": str(e)})
            except Exception as e:  # noqa: BLE001 — HTTP boundary
                return self._send(400, {"error": str(e)})

    return Handler


def serve_http(server, host: str = "127.0.0.1", port: int = 8080):
    """Start the HTTP front end on a daemon thread; returns the
    ThreadingHTTPServer (stop with .shutdown()).  port=0 binds an
    ephemeral port — read it back from `server_address`."""
    httpd = ThreadingHTTPServer((host, port), _make_handler(server))
    t = threading.Thread(target=httpd.serve_forever, daemon=True,
                         name="mx-serving-http")
    t.start()
    return httpd
