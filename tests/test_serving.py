"""mxnet_tpu.serving — dynamic-batching inference on the StableHLO
deploy path.

The contract under test (ISSUE 1 acceptance):
  * coalesced batches return outputs identical to sequential single
    calls (padding is sliced off, rows are row-independent);
  * shape-bucketing compiles each bucket AT MOST once (executor-cache
    hit/miss counters);
  * a full admission queue REJECTS (ServerOverloaded, 503 semantics)
    instead of blocking or queueing unboundedly;
  * per-request deadline expiry returns DeadlineExceeded (504);
  * graceful drain completes everything already admitted.
"""
import json
import threading
import time
import urllib.request

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, serving
from mxnet_tpu.contrib import deploy
from mxnet_tpu.gluon import nn


def _mlp(seed=0):
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu", in_units=8))
        net.add(nn.Dense(4, in_units=16))
    net.initialize(mx.initializer.Xavier(rnd_type="gaussian"), ctx=mx.cpu())
    return net


@pytest.fixture(scope="module")
def artifact(tmp_path_factory):
    """One dynamic-batch artifact shared module-wide (export + the
    first compile dominate test wall-time)."""
    d = tmp_path_factory.mktemp("serve_dyn")
    net = _mlp()
    x = nd.array(np.random.RandomState(0).rand(8, 8).astype("float32"))
    deploy.export_model(net, str(d), [x], dynamic_batch=True)
    return str(d)


def _server(artifact, **kw):
    repo = serving.ModelRepository()
    repo.add("mlp", artifact)
    cfg = serving.ServingConfig(**kw)
    return serving.InferenceServer(repo, cfg), repo


def test_coalesced_outputs_match_sequential_single_calls(artifact):
    srv, repo = _server(artifact, max_batch_size=8, batch_timeout_ms=50.0)
    served = deploy.import_model(artifact)
    xs = [nd.array(np.random.RandomState(i + 1).rand(1, 8)
                   .astype("float32")) for i in range(8)]
    futs = [srv.submit("mlp", [x]) for x in xs]
    for f, x in zip(futs, xs):
        np.testing.assert_allclose(f.result(timeout=120).asnumpy(),
                                   served(x).asnumpy(),
                                   rtol=1e-5, atol=1e-6)
    snap = srv.metrics()["models"][0]
    # the 8 submits really shared launches (coalescing happened)
    assert snap["completed"] == 8
    assert snap["batches"] < 8
    assert snap["batched_rows"] == 8
    srv.shutdown()


def test_requests_with_multiple_rows_coalesce_too(artifact):
    srv, _ = _server(artifact, max_batch_size=8, batch_timeout_ms=30.0)
    served = deploy.import_model(artifact)
    xs = [nd.array(np.random.RandomState(10 + i).rand(n, 8)
                   .astype("float32")) for i, n in enumerate((3, 2, 3))]
    futs = [srv.submit("mlp", [x]) for x in xs]
    for f, x in zip(futs, xs):
        np.testing.assert_allclose(f.result(timeout=120).asnumpy(),
                                   served(x).asnumpy(),
                                   rtol=1e-5, atol=1e-6)
    srv.shutdown()


def test_shape_buckets_compile_at_most_once(artifact):
    """Distinct row counts map onto the bucket ladder; each bucket
    compiles exactly once, repeats hit the executor cache."""
    srv, repo = _server(artifact, max_batch_size=8, batch_timeout_ms=2.0,
                        buckets=[1, 2, 4, 8])
    entry = repo.get("mlp")
    for rows in (3, 4, 2, 8, 3, 2):
        x = nd.array(np.zeros((rows, 8), "float32"))
        srv.infer("mlp", [x], timeout_ms=120000)
    # buckets touched: 4 (rows 3,4,3), 2 (rows 2,2), 8 (rows 8)
    assert entry.cache_misses == 3
    assert entry.cache_hits == 3
    snap = srv.metrics()["models"][0]
    assert snap["cache_misses"] == 3 and snap["cache_hits"] == 3
    srv.shutdown()


def test_full_admission_queue_rejects_not_blocks(artifact):
    srv, _ = _server(artifact, max_batch_size=64,
                     batch_timeout_ms=60000.0, max_queue=2)
    x = nd.array(np.zeros((1, 8), "float32"))
    f1 = srv.submit("mlp", [x])
    f2 = srv.submit("mlp", [x])
    t0 = time.monotonic()
    with pytest.raises(serving.ServerOverloaded):
        srv.submit("mlp", [x])
    # reject-fast, not block-until-room
    assert time.monotonic() - t0 < 5.0
    assert srv.metrics()["models"][0]["rejected"] == 1
    srv.shutdown(drain=True)
    assert f1.result(timeout=120).shape == (1, 4)
    assert f2.result(timeout=120).shape == (1, 4)


def test_deadline_expiry_returns_timeout_error(artifact):
    srv, _ = _server(artifact, max_batch_size=64,
                     batch_timeout_ms=60000.0)
    x = nd.array(np.zeros((1, 8), "float32"))
    fut = srv.submit("mlp", [x], timeout_ms=100)
    with pytest.raises(serving.DeadlineExceeded):
        fut.result(timeout=30)
    assert srv.metrics()["models"][0]["deadline_expired"] == 1
    srv.shutdown(drain=False)


def test_graceful_drain_completes_in_flight(artifact):
    srv, _ = _server(artifact, max_batch_size=64,
                     batch_timeout_ms=60000.0)
    x = nd.array(np.zeros((2, 8), "float32"))
    futs = [srv.submit("mlp", [x]) for _ in range(3)]
    srv.shutdown(drain=True)  # stops admission, completes the queue
    for f in futs:
        assert f.result(timeout=120).shape == (2, 4)
    with pytest.raises(serving.ServerClosed):
        srv.submit("mlp", [x])
    assert srv.pending() == 0


def test_shutdown_without_drain_fails_queued_requests(artifact):
    srv, _ = _server(artifact, max_batch_size=64,
                     batch_timeout_ms=60000.0)
    x = nd.array(np.zeros((1, 8), "float32"))
    fut = srv.submit("mlp", [x])
    srv.shutdown(drain=False)
    with pytest.raises(serving.ServerClosed):
        fut.result(timeout=30)


def test_cancel_while_queued_releases_slot_and_never_launches(artifact):
    """A client that gives up (Future.cancel) while its request is still
    queued must free its admission slot immediately, and its rows must
    never launch — the remaining requests complete untouched."""
    from concurrent.futures import CancelledError

    srv, _ = _server(artifact, max_batch_size=8,
                     batch_timeout_ms=60000.0, max_queue=2)
    x = nd.array(np.random.RandomState(40).rand(1, 8).astype("float32"))
    f1 = srv.submit("mlp", [x])
    f2 = srv.submit("mlp", [x])
    with pytest.raises(serving.ServerOverloaded):
        srv.submit("mlp", [x])  # queue is full
    assert f1.cancel()  # still queued (huge batch timeout) -> cancellable
    # the done-callback released f1's slot: admission reopens
    f3 = srv.submit("mlp", [x])
    srv.shutdown(drain=True)
    with pytest.raises(CancelledError):
        f1.result(timeout=0)
    assert f2.result(timeout=120).shape == (1, 4)
    assert f3.result(timeout=120).shape == (1, 4)
    snap = srv.metrics()["models"][0]
    # only the two live requests launched; the cancelled rows never did
    assert snap["completed"] == 2 and snap["batched_rows"] == 2
    assert srv.pending() == 0


def test_fixed_shape_artifact_pads_to_exported_batch(tmp_path):
    """A fixed-shape artifact serves partial batches: rows are padded
    up to the exported batch and sliced back off."""
    net = _mlp()
    deploy.export_model(net, str(tmp_path),
                        [nd.array(np.zeros((4, 8), "float32"))])
    repo = serving.ModelRepository()
    repo.add("fixed", str(tmp_path))
    assert repo.get("fixed").allowed_buckets([1, 2, 4, 8]) == [4]
    srv = serving.InferenceServer(
        repo, serving.ServingConfig(max_batch_size=8,
                                    batch_timeout_ms=3.0))
    x = nd.array(np.random.RandomState(5).rand(2, 8).astype("float32"))
    np.testing.assert_allclose(srv.infer("fixed", [x]).asnumpy(),
                               net(x).asnumpy(), rtol=1e-5, atol=1e-6)
    srv.shutdown()


def test_fixed_artifact_with_disagreeing_input_dims_still_serves(tmp_path):
    """Inputs that disagree on dim 0 (a lookup table beside the data
    batch) mean no padded buckets exist — but the artifact must still
    serve, one request per launch at the exact exported shapes."""
    from mxnet_tpu.gluon.block import HybridBlock

    class _Lut(HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.d = nn.Dense(4, in_units=10)

        def hybrid_forward(self, F, x, table):
            return self.d(F.dot(x, F.transpose(table)))

    net = _Lut()
    net.initialize(mx.initializer.Xavier(), ctx=mx.cpu())
    x = nd.array(np.random.RandomState(50).rand(4, 8).astype("float32"))
    table = nd.array(np.random.RandomState(51).rand(10, 8)
                     .astype("float32"))
    deploy.export_model(net, str(tmp_path), [x, table])
    repo = serving.ModelRepository()
    repo.add("lut", str(tmp_path))
    entry = repo.get("lut")
    assert entry.allowed_buckets([1, 2, 4]) == []
    assert not entry.coalescable()
    srv = serving.InferenceServer(
        repo, serving.ServingConfig(max_batch_size=8,
                                    batch_timeout_ms=60000.0))
    got = srv.infer("lut", [x, table], timeout_ms=120000)
    np.testing.assert_allclose(got.asnumpy(), net(x, table).asnumpy(),
                               rtol=1e-5, atol=1e-6)
    srv.shutdown()


def test_rejection_is_cheap_for_cold_models(artifact, tmp_path):
    """Backpressure must fail fast: rejecting a submit (queue full or
    shut down) never pays a cold model's artifact import."""
    import shutil

    shutil.copytree(artifact, tmp_path / "cold_a")
    shutil.copytree(artifact, tmp_path / "cold_b")
    repo = serving.ModelRepository()
    repo.add("hot", artifact)
    repo.add("cold_a", str(tmp_path / "cold_a"))
    repo.add("cold_b", str(tmp_path / "cold_b"))
    srv = serving.InferenceServer(
        repo, serving.ServingConfig(max_batch_size=64,
                                    batch_timeout_ms=60000.0,
                                    max_queue=1))
    x = nd.array(np.zeros((1, 8), "float32"))
    fut = srv.submit("hot", [x])
    with pytest.raises(serving.ServerOverloaded):
        srv.submit("cold_a", [x])
    assert repo.get("cold_a")._served is None  # rejected, not imported
    srv.shutdown(drain=True)
    assert fut.result(timeout=120).shape == (1, 4)
    with pytest.raises(serving.ServerClosed):
        srv.submit("cold_b", [x])
    assert repo.get("cold_b")._served is None


def test_repository_versions_and_lazy_load(artifact, tmp_path):
    net2 = _mlp()
    deploy.export_model(net2, str(tmp_path),
                        [nd.array(np.zeros((2, 8), "float32"))])
    repo = serving.ModelRepository()
    assert repo.add("mlp", artifact) == 1
    assert repo.add("mlp", str(tmp_path)) == 2
    assert repo.models() == {"mlp": [1, 2]}
    # nothing imported until traffic touches an entry
    assert repo.get("mlp", 1)._served is None
    assert repo.get("mlp")._served is None  # default = latest (v2)
    assert repo.get("mlp").version == 2
    with pytest.raises(serving.ServingError, match="versions"):
        repo.get("mlp", 7)
    with pytest.raises(serving.ServingError, match="unknown model"):
        repo.get("nope")
    # touching .served imports exactly that version's artifact
    x = nd.array(np.random.RandomState(3).rand(2, 8).astype("float32"))
    srv = serving.InferenceServer(
        repo, serving.ServingConfig(max_batch_size=4,
                                    batch_timeout_ms=2.0))
    np.testing.assert_allclose(
        srv.infer("mlp", [x], version=2).asnumpy(),
        net2(x).asnumpy(), rtol=1e-5, atol=1e-6)
    assert repo.get("mlp", 1)._served is None
    srv.shutdown()


def test_repository_scan_layout(artifact, tmp_path):
    import shutil

    root = tmp_path / "models"
    shutil.copytree(artifact, root / "mlp" / "1")
    shutil.copytree(artifact, root / "mlp" / "3")
    (root / "mlp" / "not_a_version").mkdir()
    (root / "stray.txt").write_text("x")
    repo = serving.ModelRepository()
    assert repo.scan(str(root)) == ["mlp/1", "mlp/3"]
    assert repo.models() == {"mlp": [1, 3]}


def test_request_validation_errors(artifact):
    srv, _ = _server(artifact, max_batch_size=4, batch_timeout_ms=2.0)
    with pytest.raises(serving.ServingError, match="takes 1 inputs"):
        srv.infer("mlp", [np.zeros((1, 8), "float32"),
                          np.zeros((1, 8), "float32")])
    with pytest.raises(serving.ServingError, match="dtype"):
        srv.infer("mlp", [np.zeros((1, 8), "int32")])
    with pytest.raises(serving.ServingError, match="!= exported"):
        srv.infer("mlp", [np.zeros((1, 9), "float32")])
    with pytest.raises(serving.ServingError, match="split the request"):
        srv.infer("mlp", [np.zeros((5, 8), "float32")])
    srv.shutdown()


def test_metrics_snapshot_shape_and_json(artifact):
    srv, _ = _server(artifact, max_batch_size=4, batch_timeout_ms=2.0)
    x = nd.array(np.random.RandomState(2).rand(1, 8).astype("float32"))
    for _ in range(3):
        srv.infer("mlp", [x])
    snap = json.loads(srv.dumps())
    assert snap["pending"] == 0 and snap["closed"] is False
    (mm,) = snap["models"]
    assert mm["model"] == "mlp" and mm["version"] == 1
    assert mm["requests"] == 3 and mm["completed"] == 3
    assert mm["qps"] > 0
    assert mm["p50_latency_ms"] > 0
    assert mm["p99_latency_ms"] >= mm["p50_latency_ms"]
    assert 0 < mm["batch_occupancy"] <= 1.0
    assert mm["rejected"] == 0 and mm["deadline_expired"] == 0
    srv.shutdown()


def test_scalar_side_inputs_must_match_to_share_a_batch(tmp_path):
    """Scalar (0-d) side-inputs are passed once per launch, so only
    requests with bitwise-equal scalars coalesce — and the scalar is
    honoured per request either way."""
    from mxnet_tpu.gluon.block import HybridBlock

    class _Scaled(HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.d = nn.Dense(4, in_units=8)

        def hybrid_forward(self, F, x, s):
            return self.d(x) * s

    net = _Scaled()
    net.initialize(mx.initializer.Xavier(), ctx=mx.cpu())
    x = nd.array(np.random.RandomState(11).rand(2, 8).astype("float32"))
    s = nd.array(np.float32(2.0))
    deploy.export_model(net, str(tmp_path), [x, s], dynamic_batch=True)
    repo = serving.ModelRepository()
    repo.add("scaled", str(tmp_path))
    srv = serving.InferenceServer(
        repo, serving.ServingConfig(max_batch_size=8,
                                    batch_timeout_ms=30.0))
    x1 = nd.array(np.random.RandomState(12).rand(1, 8).astype("float32"))
    f2 = srv.submit("scaled", [x1, nd.array(np.float32(2.0))])
    f3 = srv.submit("scaled", [x1, nd.array(np.float32(3.0))])
    np.testing.assert_allclose(
        f2.result(timeout=120).asnumpy(),
        net(x1, nd.array(np.float32(2.0))).asnumpy(),
        rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(
        f3.result(timeout=120).asnumpy(),
        net(x1, nd.array(np.float32(3.0))).asnumpy(),
        rtol=1e-5, atol=1e-6)
    # different scalars could NOT share a launch
    assert srv.metrics()["models"][0]["batches"] == 2
    srv.shutdown()


def test_non_coalescable_outputs_never_include_padding(tmp_path):
    """A dynamic-batch program whose output is NOT batch-major (scalar
    mean head) must run at the exact request shape: padding rows up to
    a bucket would leak zeros into the reduction."""
    from mxnet_tpu.gluon.block import HybridBlock

    class _MeanHead(HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.d = nn.Dense(4, in_units=8)

        def hybrid_forward(self, F, x):
            return self.d(x).mean()

    net = _MeanHead()
    net.initialize(mx.initializer.Xavier(), ctx=mx.cpu())
    x8 = nd.array(np.random.RandomState(30).rand(8, 8).astype("float32"))
    deploy.export_model(net, str(tmp_path), [x8], dynamic_batch=True)
    repo = serving.ModelRepository()
    repo.add("mean", str(tmp_path))
    assert not repo.get("mean").coalescable()
    srv = serving.InferenceServer(
        repo, serving.ServingConfig(max_batch_size=8,
                                    batch_timeout_ms=60000.0,
                                    buckets=[1, 2, 4, 8]))
    # rows=3 sits between buckets 2 and 4; padding to 4 would shift the
    # mean.  The huge batch timeout also proves non-coalescable
    # requests launch immediately instead of waiting for a batch.
    x = nd.array(np.random.RandomState(31).rand(3, 8).astype("float32"))
    t0 = time.monotonic()
    got = srv.infer("mean", [x], timeout_ms=120000)
    assert time.monotonic() - t0 < 30.0
    np.testing.assert_allclose(got.asnumpy(), net(x).asnumpy(),
                               rtol=1e-5, atol=1e-6)
    srv.shutdown()


def test_fixed_shape_non_coalescable_artifact_serves(tmp_path):
    """A fixed-shape export of a non-batch-major program (scalar mean
    head) must still serve: the launch shape is the artifact's exported
    batch, not the request's logical row count (which stays 1 because
    non-coalescable rows are never split back per request)."""
    from mxnet_tpu.gluon.block import HybridBlock

    class _MeanHead(HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.d = nn.Dense(4, in_units=8)

        def hybrid_forward(self, F, x):
            return self.d(x).mean()

    net = _MeanHead()
    net.initialize(mx.initializer.Xavier(), ctx=mx.cpu())
    x4 = nd.array(np.random.RandomState(40).rand(4, 8).astype("float32"))
    deploy.export_model(net, str(tmp_path), [x4])  # fixed batch of 4
    repo = serving.ModelRepository()
    repo.add("meanfix", str(tmp_path))
    entry = repo.get("meanfix")
    assert entry.fixed_batch() == 4 and not entry.coalescable()
    srv = serving.InferenceServer(
        repo, serving.ServingConfig(max_batch_size=8,
                                    batch_timeout_ms=60000.0))
    got = srv.infer("meanfix", [x4], timeout_ms=120000)
    np.testing.assert_allclose(got.asnumpy(), net(x4).asnumpy(),
                               rtol=1e-5, atol=1e-6)
    srv.shutdown()


def test_http_sheds_load_without_importing_cold_model(artifact, tmp_path):
    """The HTTP layer must honour the cheap-rejection contract too: a
    503 shed never waits behind a cold model's artifact import (the
    admission probe runs BEFORE input_specs touches the artifact)."""
    import shutil

    shutil.copytree(artifact, tmp_path / "cold")
    repo = serving.ModelRepository()
    repo.add("hot", artifact)
    repo.add("cold", str(tmp_path / "cold"))
    srv = serving.InferenceServer(
        repo, serving.ServingConfig(max_batch_size=64,
                                    batch_timeout_ms=60000.0,
                                    max_queue=1))
    httpd = serving.serve_http(srv, port=0)
    try:
        port = httpd.server_address[1]
        fut = srv.submit("hot", [nd.array(np.zeros((1, 8), "float32"))])
        body = json.dumps(
            {"inputs": [np.zeros((1, 8)).tolist()]}).encode()
        try:
            urllib.request.urlopen(urllib.request.Request(
                f"http://127.0.0.1:{port}/v1/models/cold:predict",
                data=body), timeout=30)
            raise AssertionError("expected HTTPError")
        except urllib.error.HTTPError as e:
            assert e.code == 503
        assert repo.get("cold")._served is None  # shed, not imported
        assert repo.get("cold").metrics.snapshot()["rejected"] == 1
    finally:
        httpd.shutdown()
        srv.shutdown(drain=True)
    assert fut.result(timeout=120).shape == (1, 4)


def test_http_front_end_predict_metrics_and_503(artifact):
    srv, _ = _server(artifact, max_batch_size=8, batch_timeout_ms=2.0)
    httpd = serving.serve_http(srv, port=0)
    try:
        port = httpd.server_address[1]
        base = f"http://127.0.0.1:{port}"
        x = np.random.RandomState(9).rand(1, 8).astype("float32")
        body = json.dumps({"inputs": [x.tolist()]}).encode()
        r = urllib.request.urlopen(urllib.request.Request(
            f"{base}/v1/models/mlp:predict", data=body,
            headers={"Content-Type": "application/json"}), timeout=120)
        out = json.loads(r.read())
        served = deploy.import_model(artifact)
        np.testing.assert_allclose(np.array(out["outputs"]),
                                   served(x).asnumpy(),
                                   rtol=1e-5, atol=1e-6)
        r = urllib.request.urlopen(f"{base}/v1/models", timeout=30)
        assert json.loads(r.read())["models"] == {"mlp": [1]}
        r = urllib.request.urlopen(f"{base}/v1/metrics", timeout=30)
        assert json.loads(r.read())["models"][0]["completed"] >= 1
        # unknown model is a clean 404 (client routing mistake, not a
        # server fault), not a stack trace
        try:
            urllib.request.urlopen(urllib.request.Request(
                f"{base}/v1/models/nope:predict", data=body), timeout=30)
            raise AssertionError("expected HTTPError")
        except urllib.error.HTTPError as e:
            assert e.code == 404 and "unknown model" in \
                json.loads(e.read())["error"]
    finally:
        httpd.shutdown()
        srv.shutdown()
    # after shutdown the server rejects (503 ServerClosed semantics)
    with pytest.raises(serving.ServerClosed):
        srv.submit("mlp", [np.zeros((1, 8), "float32")])


def test_concurrent_clients_all_get_correct_rows(artifact):
    """Closed-loop hammering from many threads: every response must be
    the right ROW (no cross-request mixing under concurrency)."""
    srv, _ = _server(artifact, max_batch_size=8, batch_timeout_ms=2.0)
    served = deploy.import_model(artifact)
    refs, errs = {}, []

    def client(i):
        rng = np.random.RandomState(100 + i)
        try:
            for _ in range(5):
                x = rng.rand(1, 8).astype("float32")
                got = srv.infer("mlp", [x]).asnumpy()
                np.testing.assert_allclose(got, served(x).asnumpy(),
                                           rtol=1e-5, atol=1e-6)
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(240)
    assert not errs, errs[:1]
    snap = srv.metrics()["models"][0]
    assert snap["completed"] == 30
    srv.shutdown()


# ---------------------------------------------------------------------------
# zero-downtime version rollover (ISSUE 7 satellite)
# ---------------------------------------------------------------------------

def test_rollover_pins_default_and_releases_old_version(artifact):
    repo = serving.ModelRepository()
    repo.add("m", artifact)           # v1
    repo.add("m", artifact, version=2)
    assert repo.default_version("m") == 2  # unpinned default = latest
    e1, e2 = repo.get("m", 1), repo.get("m", 2)
    x = nd.array(np.random.RandomState(5).rand(2, 8).astype("float32"))
    e1.execute(2, [x.data])           # warm v1: artifact + executor
    assert e1._served is not None and len(e1._executables) == 1

    assert repo.rollover("m", 2) == 2
    assert repo.get("m") is e2 and repo.default_version("m") == 2
    # v1 had no traffic in flight: released immediately
    assert e1.retired
    assert e1._served is None and len(e1._executables) == 0
    # explicit-version stragglers still work (lazy re-import)
    out = e1.execute(2, [x.data])
    assert np.asarray(out[0]).shape == (2, 4)
    # pinned: adding a NEWER version must not shift traffic anymore
    repo.add("m", artifact, version=3)
    assert repo.get("m") is e2
    # ...until the next rollover (here: a rollback to v1)
    repo.rollover("m", 1)
    assert repo.get("m") is e1 and not e1.retired and e2.retired


def test_rollover_concurrent_swap_drains_then_releases(artifact):
    """The concurrent-swap contract: a request in flight on the old
    version finishes on the old version's executors; the release
    happens after it completes, never under it."""
    from mxnet_tpu.resilience import chaos

    repo = serving.ModelRepository()
    repo.add("m", artifact)
    repo.add("m", artifact, version=2)
    e1, e2 = repo.get("m", 1), repo.get("m", 2)
    x = nd.array(np.random.RandomState(6).rand(2, 8).astype("float32"))
    e1.execute(2, [x.data])  # warm v1
    want = np.asarray(e1.execute(2, [x.data])[0])

    results, entered = {}, threading.Event()

    def long_request():
        entered.set()
        # the chaos hang keeps THIS request in flight while the swap
        # lands on the main thread
        results["out"] = e1.execute(2, [x.data])

    with chaos.inject("serving.execute", at=1, action="hang",
                      duration=0.6):
        t = threading.Thread(target=long_request)
        t.start()
        entered.wait(10)
        time.sleep(0.15)  # the request is inside the hang window
        assert repo.rollover("m", 2) == 2
        assert repo.get("m") is e2
        # in flight: retired but NOT released
        assert e1.retired and e1.inflight() == 1
        assert e1._served is not None and len(e1._executables) == 1
        t.join(30)
    # the old request completed correctly on the old executors...
    np.testing.assert_allclose(np.asarray(results["out"][0]), want,
                               rtol=1e-6)
    # ...and ONLY then was the entry released
    assert e1.inflight() == 0
    assert e1._served is None and len(e1._executables) == 0


def test_rollover_through_server_requests(artifact):
    """End to end through InferenceServer: version-less requests follow
    the pinned default across a rollover; nothing errors or drops."""
    repo = serving.ModelRepository()
    repo.add("m", artifact)
    repo.add("m", artifact, version=2)
    srv = serving.InferenceServer(repo, serving.ServingConfig(
        max_batch_size=4, batch_timeout_ms=1.0))
    x = nd.array(np.random.RandomState(7).rand(1, 8).astype("float32"))
    try:
        assert srv.infer("m", [x]).asnumpy().shape == (1, 4)  # on v2
        repo.rollover("m", 1)
        assert srv.infer("m", [x]).asnumpy().shape == (1, 4)  # on v1
        repo.rollover("m", 2)
        out = srv.infer("m", [x]).asnumpy()
        assert out.shape == (1, 4)
        # the retired v1 entry drained (no pending requests) and
        # released its resources
        assert repo.get("m", 1)._served is None
    finally:
        srv.shutdown(drain=True, timeout=10.0)


# ---------------------------------------------------------------------------
# mxflow-driven hardening (ISSUE 8): the MX008/MX010 findings the
# dataflow rules surfaced in serving/ are FIXED, with the concurrency
# regressions below pinning each fix.
# ---------------------------------------------------------------------------

def test_cold_import_does_not_block_entry_hot_lock(artifact, monkeypatch):
    """MX008 fix: the lazy artifact import serializes on a dedicated
    import lock — begin_use/end_use/inflight (the rollover drain path)
    must stay responsive while another thread pays a slow import."""
    repo = serving.ModelRepository()
    repo.add("mlp", artifact)
    entry = repo.get("mlp")
    importing = threading.Event()
    real_import = deploy.import_model

    def slow_import(path):
        importing.set()
        time.sleep(0.5)
        return real_import(path)

    monkeypatch.setattr(deploy, "import_model", slow_import)
    t = threading.Thread(target=lambda: entry.served)
    t.start()
    assert importing.wait(5.0)
    t0 = time.monotonic()
    entry.begin_use()
    n = entry.inflight()
    entry.end_use()
    dt = time.monotonic() - t0
    t.join()
    assert n == 1
    assert dt < 0.25, (
        f"hot entry lock blocked {dt:.3f}s behind the artifact import")
    assert entry._served is not None  # the import itself completed


def test_submit_releases_slot_when_span_teardown_fails(artifact,
                                                      monkeypatch,
                                                      tmp_path):
    """MX010 fix: once a request is enqueued, the admission slot and
    the entry use-count are owned by the done-callback — a failure in
    the submit path's OWN teardown (span bookkeeping) after enqueue
    must not strand them.  Before the fix the callback was attached
    after the finally, so a raising Span.finish leaked the slot
    forever."""
    from mxnet_tpu import profiler
    from mxnet_tpu.serving import server as server_mod

    srv, repo = _server(artifact, max_batch_size=4, batch_timeout_ms=1.0)
    x = nd.array(np.random.RandomState(3).rand(1, 8).astype("float32"))
    srv.infer("mlp", [x], timeout_ms=120000)  # warm compile first

    real_tracing = server_mod._tracing

    class _BoomSpan(real_tracing.Span):
        def finish(self):
            super().finish()
            raise RuntimeError("span teardown boom")

    class _Shim:
        Span = _BoomSpan

    monkeypatch.setattr(server_mod, "_tracing", _Shim)
    profiler.start()
    try:
        with pytest.raises(RuntimeError, match="span teardown boom"):
            srv.submit("mlp", [x], timeout_ms=120000)
    finally:
        profiler.stop()
        profiler.dump(finished=True,
                      filename=str(tmp_path / "_flush.json"))
        monkeypatch.setattr(server_mod, "_tracing", real_tracing)
    # the enqueued request still runs; its completion must release the
    # admission slot AND the entry use-count via the done-callback
    deadline = time.monotonic() + 60.0
    while (srv.pending() or repo.get("mlp").inflight()) and \
            time.monotonic() < deadline:
        time.sleep(0.01)
    assert srv.pending() == 0, "admission slot leaked"
    assert repo.get("mlp").inflight() == 0, "entry use-count leaked"
    srv.shutdown(drain=True, timeout=10.0)
