"""Central registry of `MXNET_*` environment knobs.

Every env-var knob the framework honors is DECLARED here once — name,
type, default, and documentation — and read through the typed accessors
(:func:`get_int`, :func:`get_bool`, :func:`get_str`, :func:`get_float`).
Reading an undeclared ``MXNET_*`` name raises :class:`MXNetError`, so a
typo'd knob dies at the read site instead of silently returning its
default forever (the bug class mxlint rule MX003 exists to catch).

The registry is the single source of truth for ``docs/env_vars.md``
(generated via ``python tools/mxlint.py --env-docs``) and is fully
populated at import time, so documentation can never trail the code.

Declared defaults are what the accessor returns when the variable is
unset; a call site may pass ``default=`` to override — used by knobs
whose default is computed (worker counts, probe budgets), which declare
``default=None`` and document the dynamic rule.
"""
from __future__ import annotations

import os as _os
import threading
from typing import Any, Dict, List, NamedTuple, Optional, Tuple

from ..base import MXNetError
from ..base import convert_env as _convert_env
from ..base import get_env as _raw_get_env  # the untyped low-level reader

__all__ = [
    "Knob", "Tunable", "declare", "knobs", "is_declared", "tunables",
    "get_int", "get_bool", "get_str", "get_float",
    "apply_overlay", "overlay_info", "clear_overlay",
    "resolved", "fingerprint", "generate_docs",
]


class Tunable(NamedTuple):
    """Optional search-space metadata a knob declares about itself, so
    mxtune's space is derived from the registry instead of duplicated
    beside it.  Either a numeric range (``lo``/``hi``, with ``scale``
    'linear' or 'log' — log doubles/halves under neighborhood moves) or
    an explicit ``choices`` tuple (categorical / bool knobs)."""
    lo: Optional[float] = None
    hi: Optional[float] = None
    scale: str = "linear"
    choices: Optional[Tuple[Any, ...]] = None


class Knob(NamedTuple):
    name: str
    typ: type
    default: Any
    doc: str
    tunable: Optional[Tunable] = None


_KNOBS: Dict[str, Knob] = {}
_LOCK = threading.Lock()

_UNSET = object()

# Tuned-config overlay (mxnet_tpu.autotune): name -> RAW string value,
# consulted by _get only when the process env leaves the knob unset.
# Explicit MXNET_* settings therefore always win — the overlay is a
# better default, never an override.
_OVERLAY: Dict[str, str] = {}
_OVERLAY_META: Optional[Dict[str, Any]] = None


def declare(name: str, typ: type, default: Any, doc: str,
            tunable: Optional[Tunable] = None) -> Knob:
    """Register a knob. Duplicate registration raises loudly — even an
    identical re-declaration means two call sites each believe they own
    the knob, and the second would silently shadow doc/tunable edits to
    the first. Every knob is declared exactly once, in this module."""
    if not name.startswith("MXNET_"):
        raise MXNetError(
            f"env knob {name!r} must use the MXNET_ prefix; other "
            "process env vars are not framework knobs")
    if tunable is not None and typ is bool and tunable.choices is None:
        tunable = tunable._replace(choices=(False, True))
    k = Knob(name, typ, default, doc, tunable)
    with _LOCK:
        if name in _KNOBS:
            prev = _KNOBS[name]
            raise MXNetError(
                f"env knob {name} already registered "
                f"({prev.typ.__name__}, default {prev.default!r}) — "
                "duplicate declaration; every knob is declared exactly "
                "once in mxnet_tpu/util/env.py")
        _KNOBS[name] = k
    return k


def is_declared(name: str) -> bool:
    return name in _KNOBS


def knobs() -> List[Knob]:
    """All declared knobs, sorted by name (docs generation order)."""
    with _LOCK:
        return sorted(_KNOBS.values(), key=lambda k: k.name)


def tunables() -> List[Knob]:
    """The knobs that declared :class:`Tunable` metadata — mxtune's
    search-space surface, sorted by name."""
    return [k for k in knobs() if k.tunable is not None]


def _get(name: str, typ: type, default: Any) -> Any:
    knob = _KNOBS.get(name)
    if knob is None:
        raise MXNetError(
            f"unregistered env knob {name!r} — declare it in "
            f"mxnet_tpu/util/env.py (known: {sorted(_KNOBS)[:20]}...)")
    if knob.typ is not typ:
        raise MXNetError(
            f"env knob {name} is declared as {knob.typ.__name__}, "
            f"read as {typ.__name__}")
    dflt = knob.default if default is _UNSET else default
    raw = _os.environ.get(name)
    if (raw is None or raw == "") and name in _OVERLAY:
        # precedence: explicit env (non-empty) > tuned overlay > default
        return _convert_env(name, _OVERLAY[name], typ)
    return _raw_get_env(name, dflt, typ)


def get_int(name: str, default: Any = _UNSET) -> Optional[int]:
    return _get(name, int, default)


def get_bool(name: str, default: Any = _UNSET) -> Optional[bool]:
    return _get(name, bool, default)


def get_str(name: str, default: Any = _UNSET) -> Optional[str]:
    return _get(name, str, default)


def get_float(name: str, default: Any = _UNSET) -> Optional[float]:
    return _get(name, float, default)


def apply_overlay(config: Dict[str, Any], fingerprint: str = "",
                  source: str = "") -> Dict[str, Any]:
    """Install a tuned-config overlay (mxtune startup / trial runs).

    ``config`` maps knob names to values (any JSON scalar; stored as the
    string the environment would have carried).  Precedence is fixed:
    a knob the process env sets explicitly (non-empty) keeps its env
    value — those names are recorded as ``shadowed``; unregistered names
    are recorded as ``ignored`` and dropped (a stale store entry naming
    a since-removed knob must not poison the process).  Returns the
    application record, also available via :func:`overlay_info` and
    stamped into mxprof dumps as ``tuned_config``."""
    global _OVERLAY_META
    applied, shadowed, ignored = [], [], []
    with _LOCK:
        for name in sorted(config):
            if name not in _KNOBS:
                ignored.append(name)
                continue
            raw = _os.environ.get(name)
            if raw is not None and raw != "":
                shadowed.append(name)
                continue
            value = config[name]
            _OVERLAY[name] = ("1" if value else "0") \
                if isinstance(value, bool) else str(value)
            applied.append(name)
        _OVERLAY_META = {
            "fingerprint": fingerprint,
            "source": source,
            "applied": applied,
            "shadowed": shadowed,
            "ignored": ignored,
        }
        return dict(_OVERLAY_META)


def overlay_info() -> Optional[Dict[str, Any]]:
    """The record of the last :func:`apply_overlay`, or None when no
    tuned config is active."""
    with _LOCK:
        return dict(_OVERLAY_META) if _OVERLAY_META is not None else None


def clear_overlay() -> None:
    with _LOCK:
        global _OVERLAY_META
        _OVERLAY.clear()
        _OVERLAY_META = None


# Harness control vars that legitimately use the MXNET_ prefix without
# being knobs (test seeding, nightly stage marking) — exempt from the
# unknown-env warning below.
_NON_KNOB_ENV = {"MXNET_NIGHTLY", "MXNET_TEST_SEED", "MXNET_TEST_PLATFORM"}
_warned_unknown_env = False


def _warn_unknown_env_once() -> None:
    """Warn (once per process) about MXNET_* env vars that match no
    registered knob — a typo'd knob is otherwise silently ignored
    forever.  Runs at the first resolved() call, i.e. the first time
    anything snapshots the configuration surface."""
    global _warned_unknown_env
    with _LOCK:
        if _warned_unknown_env:
            return
        _warned_unknown_env = True
        known = sorted(_KNOBS)
    import difflib
    import warnings

    for name in sorted(_os.environ):
        if (not name.startswith("MXNET_") or name in _KNOBS
                or name in _NON_KNOB_ENV):
            continue
        close = difflib.get_close_matches(name, known, n=1)
        hint = f" — did you mean {close[0]}?" if close else ""
        warnings.warn(
            f"env var {name} is not a registered MXNET_ knob and has "
            f"no effect{hint} (see docs/env_vars.md)",
            RuntimeWarning, stacklevel=3)


def resolved() -> Dict[str, Any]:
    """Every declared knob's RESOLVED value (env override, tuned
    overlay, or declared default; dynamic defaults resolve to None).
    This is the performance-relevant configuration surface of the
    process — what a bench artifact records so `perf_compare` can say
    "a knob changed" instead of just "it got slower"."""
    _warn_unknown_env_once()
    _GET = {int: get_int, bool: get_bool, str: get_str,
            float: get_float}
    out = {}
    for k in knobs():
        try:
            out[k.name] = _GET[k.typ](k.name)
        except Exception:  # noqa: BLE001 — one bad value must not hide the rest
            out[k.name] = "<unreadable>"
    return out


def fingerprint() -> str:
    """sha256 over the sorted resolved knob table — the one-line
    "did any registered knob change" answer regression attribution
    compares across runs."""
    import hashlib

    h = hashlib.sha256()
    for name, value in sorted(resolved().items()):
        h.update(f"{name}={value!r}\x1f".encode())
    return h.hexdigest()


def generate_docs() -> str:
    """Markdown reference for every declared knob (docs/env_vars.md)."""
    lines = [
        "# Environment variables",
        "",
        "Generated from the knob registry (`mxnet_tpu/util/env.py`) by",
        "`python tools/mxlint.py --env-docs`.  **Do not edit by hand** —",
        "a tier-1 test (`tests/test_mxlint.py`) fails when this file is",
        "out of sync with the registry.",
        "",
        "| Variable | Type | Default | Description |",
        "|---|---|---|---|",
    ]
    for k in knobs():
        dflt = "*(dynamic)*" if k.default is None else f"`{k.default!r}`"
        doc = " ".join(k.doc.split())
        lines.append(f"| `{k.name}` | {k.typ.__name__} | {dflt} | {doc} |")
    lines.append("")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# The knob catalogue.  One declaration per knob the framework honors;
# grouped by subsystem.  Keep alphabetical within each group.
# ---------------------------------------------------------------------------

# -- engine / dispatch ------------------------------------------------------
declare("MXNET_ENGINE_TYPE", str, "ThreadedEnginePerDevice",
        "Execution engine. 'ThreadedEnginePerDevice' (default) is the "
        "async PjRt dispatch path; 'NaiveEngine' makes every op call "
        "block_until_ready for debugging (ref: src/engine/naive_engine.cc).")
declare("MXNET_CPU_WORKER_NTHREADS", int, None,
        "Worker threads of the native dependency engine. Default is "
        "computed: max(2, os.cpu_count()).")
declare("MXNET_USE_NATIVE", bool, True,
        "Load/build the native C++ modules (engine, RecordIO, image "
        "pipeline). 0 forces the pure-Python fallbacks.")

# -- contexts / memory ------------------------------------------------------
declare("MXNET_DEFAULT_CONTEXT", str, None,
        "Force the default device context ('cpu' or 'tpu'). Default is "
        "computed: tpu(0) when an accelerator is visible, else cpu(0).")
declare("MXNET_GPU_MEM_POOL_RESERVE", int, None,
        "Percent of device memory kept OUT of the allocator pool "
        "(reference spelling); mapped to XLA_PYTHON_CLIENT_MEM_FRACTION "
        "at import. Unset = XLA default.")

# -- training ---------------------------------------------------------------
declare("MXNET_BACKWARD_DO_MIRROR", bool, False,
        "Gradient mirroring: recompute activations in the backward "
        "(jax.checkpoint) instead of keeping them in HBM — trades MXU "
        "FLOPs for memory.")
declare("MXNET_FUSED_BUCKET_BYTES", int, 4 << 20,
        "Bucket size for the fused gradient allreduce "
        "(KVStore.pushpull_fused): one collective per ~this many bytes "
        "of dtype-homogeneous dense gradients.",
        tunable=Tunable(lo=256 << 10, hi=64 << 20, scale="log"))
declare("MXNET_FUSED_OPTIMIZER", bool, False,
        "SPMD trainer: concatenate fully-replicated parameters into one "
        "flat optimizer update. Default off — profiling showed the 1-D "
        "concat destroys conv-weight tiled layouts and donation aliasing.")
declare("MXNET_KVSTORE_TIMEOUT", float, None,
        "Seconds a distributed collective may block before the worker "
        "aborts loudly instead of hanging on a dead peer. Unset/0 = wait "
        "forever.")
declare("MXNET_SPMD", bool, False,
        "Route Trainer.step through the unified GSPMD path: ONE donated "
        "jit program over the replica mesh (gradient reduce + sharded "
        "optimizer apply) instead of N per-replica dispatches. "
        "Trainer(spmd=...) overrides per trainer. Transparent per-step "
        "fallback to the per-replica path for sparse gradients, ragged "
        "layouts, or optimizers without a fused form. See "
        "docs/sharding.md.")
declare("MXNET_ZERO_STATES", bool, True,
        "Under the SPMD step path, shard optimizer states (and the "
        "weight-update computation) across the data-parallel axis "
        "(ZeRO-1 / arXiv:2004.13336): reduce-scatter grads, update the "
        "local state shard, all-gather fresh weights. 0 keeps states "
        "replicated (the collective is then a plain all-reduce).")
declare("MXNET_ZERO_MIN_SIZE", int, 2048,
        "Smallest parameter (elements) whose optimizer states shard "
        "across the data axis under MXNET_ZERO_STATES: big tensors "
        "carry the memory, tiny biases would pay collective latency "
        "for nothing and stay replicated.",
        tunable=Tunable(lo=256, hi=65536, scale="log"))
declare("MXNET_SPMD_BUCKET_BYTES", int, 0,
        "Bucket size for the SPMD mesh-collective gradient reduce "
        "(KVStore.pushpull_fused under MXNET_SPMD=1). 0 = inherit "
        "MXNET_FUSED_BUCKET_BYTES.")
declare("MXNET_COMM_QUANT", str, "none",
        "Wire encoding for the SPMD bucket collectives (the gradient "
        "reduce and the fresh-weight gather in optimizer/spmd.py, and "
        "KVStore.pushpull_fused's SPMD bucket all-reduce): 'int8' "
        "(symmetric linear, 1 byte/elem) or 'fp8' (e4m3 emulation, "
        "1 byte/elem) quantize with per-512-element-block scales and error-feedback "
        "residuals; 'none' keeps full-precision collectives. See "
        "docs/sharding.md#quantized-collectives.",
        tunable=Tunable(choices=("none", "int8", "fp8")))
declare("MXNET_COMM_QUANT_EF", bool, True,
        "Carry error-feedback residuals for MXNET_COMM_QUANT (the "
        "quantization remainder re-enters the next step's payload "
        "before encoding). Disable ONLY for A/B experiments — without "
        "feedback the rounding bias accumulates into the weights.",
        tunable=Tunable())
declare("MXNET_COMM_QUANT_MIN_SIZE", int, 2048,
        "Smallest bucket (padded elements) MXNET_COMM_QUANT encodes; "
        "tiny buckets stay fp32 — their scale rows and encode/decode "
        "work would cost more than the bytes they save.",
        tunable=Tunable(lo=256, hi=262144, scale="log"))
declare("MXNET_COMM_OVERLAP", bool, False,
        "Dispatch each SPMD bucket's gradient reduce as its own "
        "program, issued in gradient-ready (reverse-bucket) order "
        "while the backward is still executing, so collectives overlap "
        "compute and the step approaches max(compute, comm) instead "
        "of their sum. See docs/performance.md.",
        tunable=Tunable())

# -- ops / kernels ----------------------------------------------------------
declare("MXNET_BN_EXACT_VAR", bool, False,
        "BatchNorm uses the exact two-pass variance instead of the "
        "single-pass shifted estimator; also disables the fused Conv+BN "
        "path (whose statistics are inherently single-pass).")
declare("MXNET_FUSED_CONVBN", bool, False,
        "Route ResNet V1 residual blocks through the fused Pallas "
        "Conv+BN+ReLU kernels when tracing in NHWC layout.")
declare("MXNET_FUSED_CONVBN_BWD", bool, False,
        "Opt-in Pallas backward for the fused Conv+BN units (roughly "
        "doubles the probe-compile surface; see "
        "MXNET_PALLAS_PROBE_BUDGET).")
declare("MXNET_PALLAS_INTERPRET", bool, False,
        "Run Pallas kernels in interpreter mode (CPU testing): no "
        "Mosaic compile, bit-accurate reference semantics.")
declare("MXNET_PALLAS_PROBE_BUDGET", float, None,
        "Cumulative seconds of probe-compiles allowed when deciding "
        "whether a Pallas kernel supports a shape. Default is computed: "
        "600 when MXNET_FUSED_CONVBN_BWD=1, else 300.")
declare("MXNET_USE_PALLAS", bool, True,
        "Master switch for Pallas kernels (flash attention, fused "
        "Conv+BN). 0 selects the XLA fallbacks with identical "
        "semantics.")

# -- compile cache ----------------------------------------------------------
declare("MXNET_COMPILE_CACHE_BYTES", int, 0,
        "Byte cap for the on-disk compile cache; least-recently-used "
        "entries are evicted past it. 0 = unbounded (size the volume "
        "instead).")
declare("MXNET_COMPILE_CACHE_DIR", str, "",
        "Directory of the persistent (cross-process) AOT executable "
        "cache. Empty = persistent cache off; call sites keep their "
        "in-process caches either way. See docs/compile_cache.md.")
declare("MXNET_COMPILE_CACHE_DISABLE", bool, False,
        "Kill switch: 1 ignores MXNET_COMPILE_CACHE_DIR and compiles "
        "everything fresh (e.g. when a shared cache volume is "
        "suspected bad).")
declare("MXNET_COMPILE_CACHE_OPS", bool, False,
        "Opt-in: route the ops-registry jit/grad executables through "
        "the persistent compile cache (AOT per input signature). "
        "Serving buckets and the fused optimizer step use the cache "
        "whenever MXNET_COMPILE_CACHE_DIR is set; eager per-op "
        "programs are many and small, so they are opt-in.")
declare("MXNET_FUSED_CACHE_MAX", int, 256,
        "Entry cap of the in-process FusedUpdater executable cache "
        "(LRU eviction past it). One entry per optimizer/tree/shape "
        "signature per device.",
        tunable=Tunable(lo=32, hi=1024, scale="log"))
declare("MXNET_OP_CACHE_MAX", int, 4096,
        "Entry cap of each in-process ops-registry executable cache "
        "(jit and grad, LRU eviction past it). One entry per "
        "(op, attrs) — plus signature when MXNET_COMPILE_CACHE_OPS=1.",
        tunable=Tunable(lo=512, hi=16384, scale="log"))

# -- autotune ---------------------------------------------------------------
declare("MXNET_AUTOTUNE", bool, True,
        "Apply the stored tuned knob config (mxtune) at import when the "
        "config store has a matching winner: tuned values become the "
        "process defaults via an env-overlay that any explicitly set "
        "MXNET_* variable always overrides. 0 boots on declared "
        "defaults only. See docs/autotune.md.")
declare("MXNET_AUTOTUNE_DIR", str, "",
        "Directory of the persistent tuned-config store "
        "(autotune.store). Empty = derive <MXNET_COMPILE_CACHE_DIR>/"
        "autotune when the compile cache dir is set, else the store "
        "is off and startup never applies a tuned config.")
declare("MXNET_AUTOTUNE_SCENARIO", str, "",
        "Scenario tag the startup overlay matches store entries "
        "against (a model fingerprint or a named bench scenario such "
        "as 'mlp_train'). Empty = accept the newest entry for this "
        "framework version regardless of scenario.")
declare("MXNET_AUTOTUNE_TRIAL_TIMEOUT_S", float, 120.0,
        "Wall-clock budget of one autotune trial subprocess "
        "(tools/autotune.py). Past it the trial is killed and counted "
        "as pruned — a hung or crashed trial must never crash the "
        "tune itself.")

# -- data pipeline ----------------------------------------------------------
declare("MXNET_PREFETCH_DEPTH", int, None,
        "DataLoader prefetch depth: batches each iterator keeps in "
        "flight ahead of the consumer, in both the process and thread "
        "worker pools. Default is computed: 2 * num_workers. The "
        "DataLoader(prefetch=) argument overrides per loader.",
        tunable=Tunable(lo=1, hi=16, scale="log"))

# -- resilience -------------------------------------------------------------
declare("MXNET_BREAKER_COOLDOWN_MS", float, 1000.0,
        "Serving circuit breaker: milliseconds an OPEN breaker waits "
        "before letting one half-open probe request through.",
        tunable=Tunable(lo=100.0, hi=5000.0, scale="log"))
declare("MXNET_BREAKER_THRESHOLD", int, 5,
        "Serving circuit breaker: consecutive executor failures that "
        "open the breaker (that model answers 503 until a probe "
        "succeeds; the process never dies).")
declare("MXNET_CHAOS", bool, False,
        "Master switch for the fault-injection harness "
        "(resilience.chaos). Off = every injection site is a single "
        "falsy flag check with zero behavior change.")
declare("MXNET_CHAOS_SEED", int, 0,
        "Seed for probabilistic chaos plans (kind@pF in "
        "MXNET_CHAOS_SPEC) — schedules replay deterministically.")
declare("MXNET_CHAOS_SPEC", str, "",
        "Comma-separated chaos plans installed at import when "
        "MXNET_CHAOS=1: 'kind@N' (fail Nth call), 'kind@xN' (next N), "
        "'kind@pF' (probability F), optional ':action' "
        "(error/die/hang/preempt). See docs/resilience.md.")
declare("MXNET_CKPT_EVERY", int, 0,
        "Auto-checkpoint cadence in optimizer steps (resilience."
        "AutoCheckpoint default). 0 = only preemption-triggered saves.")
declare("MXNET_CKPT_KEEP", int, 3,
        "Auto-checkpoint retention: keep the last K step directories, "
        "prune older ones after each successful save.")
declare("MXNET_ELASTIC", bool, False,
        "Set by the elastic supervisor (tools/elastic_run.py) in every "
        "worker's env: this process runs under coordinated rank-failure "
        "recovery (heartbeats, reserved exit codes, commit-marker "
        "resume). Never set by hand; off = zero elastic code on the "
        "step path. See docs/resilience.md (Elastic recovery).")
declare("MXNET_ELASTIC_DIR", str, "",
        "Shared coordination directory of an elastic job: per-rank "
        "heartbeat stamps (hb-rank<k>.json), per-rank checkpoint "
        "subdirs (rank<k>/step-N), the job-level COMMIT.json resume "
        "marker, and per-generation worker logs. Exported by the "
        "supervisor.")
declare("MXNET_ELASTIC_RANK", int, None,
        "This worker's job rank, exported by the elastic supervisor "
        "(also what chaos rank= plan selectors match against). Default "
        "is dynamic: unset outside an elastic job.")
declare("MXNET_ELASTIC_WORLD", int, None,
        "The elastic job's current world size (shrink-mode restarts "
        "re-export a smaller value). Default is dynamic: unset outside "
        "an elastic job.")
declare("MXNET_ELASTIC_HEARTBEAT_S", float, 2.0,
        "Interval of the background heartbeat thread "
        "(resilience.heartbeat.HeartbeatWriter.start()); per-step "
        "beat() calls ignore it.")
declare("MXNET_ELASTIC_HEARTBEAT_TIMEOUT_S", float, 30.0,
        "Heartbeat age past which the supervisor declares a "
        "still-running rank HUNG and opens a failure epoch. Also the "
        "default MXNET_KVSTORE_TIMEOUT the supervisor exports so "
        "survivors' collective watchdogs fire instead of waiting "
        "forever on the dead peer.")
declare("MXNET_ELASTIC_MAX_RESTARTS", int, 3,
        "Restart budget of the elastic supervisor: failure epochs "
        "beyond this declare the job dead instead of thrashing "
        "restarts against a persistent fault.")
declare("MXNET_ELASTIC_GRACE_S", float, 30.0,
        "Seconds the supervisor waits after SIGTERMing survivors for "
        "them to cut their sync checkpoint and exit with a reserved "
        "rc; anything still alive is SIGKILLed and classified hung. "
        "Raised automatically to the collective watchdog timeout + 5s "
        "when that is longer.")
declare("MXNET_DRAIN_TIMEOUT_MS", float, 30000.0,
        "Hard deadline for InferenceServer.shutdown(drain=True): past "
        "it, still-queued requests fail with ServerClosed instead of "
        "the shutdown hanging forever on a wedged batch.")
declare("MXNET_RANKCHECK", bool, True,
        "Master switch of the runtime collective-schedule ledger "
        "(parallel.schedule): every collective site appends "
        "(site, op, dtype, nbytes, seq) to a rolling fingerprint, and "
        "a collective watchdog timeout compares fingerprints across "
        "ranks to reclassify schedule divergence (a deterministic "
        "program bug — see mxlint MX019/MX020) as ScheduleDivergence "
        "instead of burning restarts on PeerFailed. Off = one boolean "
        "check per collective.")
declare("MXNET_RANKCHECK_WINDOW", int, 256,
        "Entries kept in the rolling collective-schedule fingerprint "
        "window (minimum 8). Divergence older than the window on BOTH "
        "ranks cannot be pinpointed; larger windows cost only memory "
        "and stamp-file size.")
declare("MXNET_RANKCHECK_WAIT_S", float, 3.0,
        "How long the collective-watchdog timeout path polls peers' "
        "schedule fingerprints before giving up and keeping the "
        "PeerFailed classification. Bounded so a genuinely dead peer "
        "(no fingerprint forthcoming) only delays the failure epoch "
        "by this much.")
declare("MXNET_RETRY_BASE_MS", float, 50.0,
        "Retry policy: first backoff delay in milliseconds (doubles "
        "per attempt, jittered ±50%, capped at MXNET_RETRY_MAX_MS).",
        tunable=Tunable(lo=10.0, hi=500.0, scale="log"))
declare("MXNET_RETRY_BUDGET_MS", float, 10000.0,
        "Retry policy: hard wall-clock budget across all attempts of "
        "one call, including backoff sleeps.")
declare("MXNET_RETRY_MAX_ATTEMPTS", int, 3,
        "Retry policy: total attempts per retryable call site "
        "(1 = no retry). Only transient errors retry.")
declare("MXNET_RETRY_MAX_MS", float, 2000.0,
        "Retry policy: backoff delay ceiling in milliseconds.",
        tunable=Tunable(lo=500.0, hi=10000.0, scale="log"))

# -- observability ----------------------------------------------------------
declare("MXNET_BLACKBOX", bool, False,
        "Enable mxblackbox, the always-on crash-forensics layer, at "
        "import: a bounded per-rank event journal (ring + append-only "
        "spill file) fed by alert transitions, health events, chaos "
        "fires, retry exhaustions, checkpoint/commit and elastic "
        "lifecycle events, plus crash-bundle emission on every "
        "abnormal-exit path. mxblackbox.enable() does the same at "
        "runtime. See docs/observability.md (Crash forensics).")
declare("MXNET_BLACKBOX_DIR", str, "mxblackbox",
        "Directory for mxblackbox artifacts: per-rank journal spill "
        "files, crash-bundle directories, per-rank bundle indexes, "
        "and supervisor INCIDENT-epoch<N>.json reports. The elastic "
        "Supervisor exports <dir>/blackbox to its workers.")
declare("MXNET_BLACKBOX_GEN", int, None,
        "Elastic generation number stamped into journal entries and "
        "crash-bundle metadata. Exported by the Supervisor to each "
        "worker generation; postmortem filters bundles by it.")
declare("MXNET_BLACKBOX_HISTORY", int, 64,
        "Crash-bundle index depth: each per-rank index file keeps "
        "the newest N bundle entries (the mxtriage capture-history "
        "shape; bundle directories themselves are not deleted).")
declare("MXNET_BLACKBOX_RING", int, 512,
        "Event-journal in-memory ring capacity (entries). The ring "
        "is what a crash bundle embeds; the on-disk spill file keeps "
        "the longer history.")
declare("MXNET_BLACKBOX_SPILL_MB", int, 8,
        "Event-journal spill-file size bound in MiB. Past it the "
        "spill rotates once to a '.1' suffix, bounding disk use at "
        "roughly twice this value per rank.")
declare("MXNET_BLACKBOX_STDERR_TAIL_KB", int, 64,
        "Per-rank stderr tail bound in KiB: the Supervisor keeps at "
        "most this much of each worker's stderr file per generation "
        "and attaches it to supervisor-side scrape bundles.")
declare("MXNET_BLACKBOX_TAIL", int, 200,
        "Journal-tail depth embedded in a crash bundle (newest N "
        "entries), and the scrape depth when the supervisor reads a "
        "dead rank's spill file.")
declare("MXNET_GOODPUT", bool, False,
        "Enable mxgoodput, the job-level goodput/badput wall-clock "
        "ledger, at import: productive step seconds vs compile / "
        "data_wait / checkpoint / preemption-recovery / retry-backoff "
        "/ comm-stall badput, summing to wall-clock. Rides the mxprof "
        "flight recorder; mxgoodput.enable() does the same at "
        "runtime. See docs/observability.md (Goodput accounting).")
declare("MXNET_GOODPUT_MIN", float, 0.9,
        "Goodput-ratio alert floor: the stock goodput_rules table "
        "(telemetry.alerts) pages when mx_goodput_ratio drops below "
        "this for the rule's for_-duration. Also the default "
        "production bar tools/goodput_report.py documents.")
declare("MXNET_GOODPUT_UNATTRIBUTED_MAX", float, 0.5,
        "Clean-run noise floor for the goodput known-answer gate "
        "(tools/goodput_report.py): the fraction of wall-clock a "
        "clean run may leave unattributed (host-side Python between "
        "spans) before the gate fails. Production jobs with real "
        "step times sit far below it.")
declare("MXNET_HEALTH", bool, False,
        "Enable mxhealth, the in-graph numerics telemetry layer, at "
        "import: the fused/SPMD step programs additionally emit "
        "grad/update/param norms and a global nonfinite count as tiny "
        "extra outputs of the already-compiled step (no extra "
        "dispatch). mxhealth.enable() does the same at runtime. See "
        "docs/observability.md (Training health).")
declare("MXNET_HEALTH_ALERT_TICK_MS", float, 1000.0,
        "Interval of the alert-engine background ticker "
        "(telemetry.alerts.AlertEngine.start()) in milliseconds.")
declare("MXNET_HEALTH_EVERY", int, 1,
        "Host-fetch cadence of the mxhealth numerics outputs: every "
        "Nth step's norms/nonfinite-count are handed to the monitor "
        "(asynchronously — the step never blocks on the fetch). The "
        "in-graph skip_step guard runs EVERY step regardless, and "
        "the raise policy checks every step synchronously (a "
        "cadence-skipped NaN step would otherwise be written back "
        "before the raise).",
        tunable=Tunable(lo=1, hi=64, scale="log"))
declare("MXNET_HEALTH_POLICY", str, "record",
        "What a nonfinite gradient step does: 'record' (event + "
        "metrics only), 'raise' (NonFiniteGradient from Trainer.step, "
        "params left at their pre-step values), or 'skip_step' "
        "(in-graph guard keeps params AND optimizer states "
        "bit-identical to the pre-step values, training continues).")
declare("MXNET_HEALTH_RATIO_MAX", float, 0.1,
        "Update/param-ratio drift threshold: a health sample whose "
        "update-norm / param-norm exceeds this records an "
        "'update-ratio' event (a healthy step moves parameters by a "
        "small fraction of their magnitude). 0 disables the check.")
declare("MXNET_HEALTH_RING", int, 512,
        "mxhealth bounded history: the last N health samples and the "
        "last N detector events are kept; memory is flat no matter "
        "how long the job runs.")
declare("MXNET_HEALTH_SPIKE_K", float, 8.0,
        "Rolling median/MAD spike threshold: a loss or grad-norm "
        "sample more than K median-absolute-deviations above the "
        "rolling median records a spike event.")
declare("MXNET_HEALTH_WINDOW", int, 64,
        "Window (samples) of the rolling median/MAD spike detectors "
        "for loss and grad-norm.")
declare("MXNET_IR_AUDIT", bool, False,
        "Enable mxir, the StableHLO program auditor, at every "
        "executable-cache compile (fused step, SpmdUpdater, "
        "SPMDTrainer, serving buckets): rules MX014-MX018 run over "
        "the lowered module text and violations increment "
        "mx_ir_violations_total{rule}. Opt-in; audit-off overhead is "
        "one boolean check per compile. See docs/static_analysis.md "
        "(Program audits).")
declare("MXNET_IR_OUT", str, "",
        "When set (and MXNET_IR_AUDIT is on), path the runtime audit "
        "hook rewrites with the cumulative MXIR.json report after "
        "each audited compile.")
declare("MXNET_IR_REPL_BYTES", int, 64 << 20,
        "MX015 threshold in bytes: a tensor at least this large "
        "pinned or returned REPLICATED in a multi-partition program "
        "is an oversized-replicated violation (every device "
        "materializes the full value - the PR 18 gather-replication "
        "bug class).")
declare("MXNET_IR_WIRE_TOL", float, 0.25,
        "MX017 drift tolerance: relative disagreement allowed between "
        "the static per-program wire-bytes model and the measured "
        "mx_collective_wire_bytes_total lane before the drift itself "
        "becomes a violation. The default absorbs the ~0.8% "
        "quant-scale overhead the static model does not price.")
declare("MXNET_PROFILER_AUTOSTART", bool, False,
        "Start the chrome-trace profiler at import (ref: "
        "MXNET_PROFILER_AUTOSTART).")
declare("MXNET_SAN", bool, False,
        "Enable mxsan, the runtime concurrency & dispatch sanitizer, "
        "at import — lock-order graph, Eraser-style lockset races on "
        "tracked caches, recompile-storm detection. Opt-in; see "
        "docs/static_analysis.md (Dynamic analysis).")
declare("MXNET_SAN_OUT", str, "MXSAN.json",
        "Path the mxsan pytest plugin writes its JSON report to at "
        "session end (relative to the working directory).")
declare("MXNET_SAN_SUPPRESS", str, "",
        "Comma-separated substrings; an mxsan violation whose message "
        "contains one is dropped — the escape hatch for a finding "
        "that is understood and accepted (document why where you set "
        "it).")
declare("MXNET_TELEMETRY", bool, False,
        "Enable telemetry span tracing at import (metrics are always "
        "on; this turns on trace-event emission — see "
        "docs/observability.md).")
declare("MXNET_MXPROF", bool, False,
        "Enable the mxprof flight recorder at import: an always-on "
        "(not capture-window-gated) ring buffer of per-step "
        "attribution records — phase seconds, collective bytes, "
        "data-wait, compile events, MFU, HBM. telemetry.enable() also "
        "engages it; dump via mxprof.dump() or SIGUSR2. See "
        "docs/observability.md (mxprof).")
declare("MXNET_MXPROF_RING", int, 512,
        "mxprof flight-recorder capacity: the last N step records are "
        "kept in a bounded ring; older steps fall off. Memory is flat "
        "no matter how long the job runs.")
declare("MXNET_MXPROF_HBM_EVERY", int, 0,
        "Sample per-device HBM allocator stats every N closed step "
        "records (0 = only on dump/snapshot). Allocator stats are one "
        "cheap PjRt call; the live-array fallback scan only runs on "
        "explicit dumps.")
declare("MXNET_MXPROF_DUMP", str, "",
        "Path the SIGUSR2 handler writes the mxprof flight-recorder "
        "dump to. Empty = mxprof-rank<r>.json in the working "
        "directory once dist.init() stamped the process rank "
        "(containerized multi-host ranks share pids and must not "
        "clobber on a shared filesystem), else mxprof-<pid>.json.")
declare("MXNET_TRIAGE_DIR", str, "mxtriage",
        "Base directory mxtriage deep-capture artifacts land in (one "
        "subdirectory per capture, indexed in index.json beside them). "
        "Relative paths resolve against the working directory.")
declare("MXNET_TRIAGE_SECONDS", float, 3.0,
        "Default wall-clock window of a deep capture when the caller "
        "passes neither steps= nor seconds= (SIGUSR1 and bare "
        "POST /profilez use it).")
declare("MXNET_TRIAGE_ALERT_INTERVAL_S", float, 600.0,
        "Minimum seconds between alert-triggered deep captures "
        "(action='deep_capture' rules): a flapping alert must not turn "
        "the profiler into a DoS on its own process. Suppressed "
        "triggers are counted in mx_triage_suppressed_total.")
declare("MXNET_TRIAGE_STEP_TIMEOUT_S", float, 60.0,
        "Watchdog for steps=N deep captures: if the expected step "
        "boundaries stop arriving (training stalled or finished), the "
        "capture force-stops after this many seconds instead of "
        "holding the admission slot forever.")
declare("MXNET_TRIAGE_HISTORY", int, 64,
        "Entries kept in the mxtriage capture index (index.json); "
        "older capture records rotate out of the index (their artifact "
        "directories are left on disk).")
declare("MXNET_PEAK_FLOPS", float, None,
        "Per-device peak FLOP/s used as the MFU denominator "
        "(mx_step_mfu). Unset = resolved from the device kind table "
        "(known TPU generations); unknown devices report MFU as null "
        "rather than a made-up ratio.")

# -- init / test harness ----------------------------------------------------
declare("MXNET_TEST_DEFAULT_CONTEXT", str, "",
        "Test-suite context override: 'tpu' or 'cpu' "
        "(ref: test_utils.default_context).")
declare("MXNET_USE_SIGNAL_HANDLER", bool, True,
        "Install faulthandler crash signal handlers at import (ref: "
        "src/initialize.cc).")
