"""Random sampling ops (ref: src/operator/random/sample_op.cc).

Each op takes an explicit threefry key as its first input (threaded by the
frontend from mxnet_tpu.random) — stateless under the hood, stateful at the
MXNet-compatible API surface.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register_op


def _dt(dtype):
    return jnp.dtype(dtype if dtype not in (None, "None") else "float32")


@register_op("_random_uniform", differentiable=False, aliases=("random_uniform",))
def _uniform(key, low=0.0, high=1.0, shape=(), dtype="float32"):
    return jax.random.uniform(key, tuple(shape), _dt(dtype), low, high)


@register_op("_random_normal", differentiable=False,
             aliases=("random_normal", "normal_op"))
def _normal(key, loc=0.0, scale=1.0, shape=(), dtype="float32"):
    return loc + scale * jax.random.normal(key, tuple(shape), _dt(dtype))


@register_op("_random_randint", differentiable=False)
def _randint(key, low=0, high=1, shape=(), dtype="int32"):
    return jax.random.randint(key, tuple(shape), low, high, _dt(dtype))


@register_op("_random_gamma", differentiable=False)
def _gamma(key, alpha=1.0, beta=1.0, shape=(), dtype="float32"):
    return jax.random.gamma(key, alpha, tuple(shape), _dt(dtype)) * beta


@register_op("_random_exponential", differentiable=False)
def _exponential(key, lam=1.0, shape=(), dtype="float32"):
    return jax.random.exponential(key, tuple(shape), _dt(dtype)) / lam


@register_op("_random_poisson", differentiable=False)
def _poisson(key, lam=1.0, shape=(), dtype="float32"):
    return jax.random.poisson(key, lam, tuple(shape)).astype(_dt(dtype))


@register_op("_random_bernoulli", differentiable=False)
def _bernoulli(key, p=0.5, shape=(), dtype="float32"):
    return jax.random.bernoulli(key, p, tuple(shape)).astype(_dt(dtype))


def _multinomial_nout(attrs):
    return 2 if attrs.get("get_prob", False) else 1


@register_op("_sample_multinomial", differentiable=False,
             num_outputs=_multinomial_nout)
def _multinomial(key, data, shape=(), get_prob=False, dtype="int32"):
    logits = jnp.log(jnp.maximum(data, 1e-30))
    n = int(shape[0]) if shape else 1
    if data.ndim == 1:
        out = jax.random.categorical(key, logits, shape=(n,))
    else:
        out = jax.random.categorical(key, logits[:, None, :], axis=-1,
                                     shape=(data.shape[0], n))
    if shape == ():
        out = out.squeeze(-1) if data.ndim > 1 else out[0]
    sample = out.astype(_dt(dtype))
    if get_prob:
        logp = jax.nn.log_softmax(logits, axis=-1)
        if data.ndim == 1:
            lp = jnp.take(logp, out)
        else:
            lp = jnp.take_along_axis(
                logp, out.reshape(data.shape[0], -1).astype(jnp.int32),
                axis=-1).reshape(out.shape)
        return sample, lp
    return sample


@register_op("_shuffle", differentiable=False, aliases=("shuffle",))
def _shuffle(key, data):
    return jax.random.permutation(key, data, axis=0)


@register_op("_random_gumbel", differentiable=False)
def _gumbel(key, loc=0.0, scale=1.0, shape=(), dtype="float32"):
    return loc + scale * jax.random.gumbel(key, tuple(shape), _dt(dtype))


@register_op("_random_laplace", differentiable=False)
def _laplace(key, loc=0.0, scale=1.0, shape=(), dtype="float32"):
    return loc + scale * jax.random.laplace(key, tuple(shape), _dt(dtype))


@register_op("_random_negative_binomial", differentiable=False)
def _neg_binomial(key, k=1, p=1.0, shape=(), dtype="float32"):
    k1, k2 = jax.random.split(key)
    lam = jax.random.gamma(k1, k, tuple(shape)) * (1 - p) / p
    return jax.random.poisson(k2, lam, tuple(shape)).astype(_dt(dtype))
