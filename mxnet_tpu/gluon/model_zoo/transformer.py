"""Transformer NMT encoder-decoder (BASELINE config 5: Transformer en-de).

Counterpart of the Sockeye/GluonNLP transformer stack the reference
ecosystem provides (ref: gluonnlp model/transformer.py; Sockeye
transformer layers; the reference's long-sequence mechanism is bucketing —
BucketingModule, SURVEY.md §5).

TPU-first design: one XLA program per sequence-length bucket (the jit
cache keys on shapes — exactly the reference's executor-per-bucket
design); attention runs through the fused `dot_product_attention` op
(Pallas on TPU) with in-kernel causal masking for the decoder; sinusoidal
position tables are baked as constants (folded by XLA).
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from ...base import MXNetError
from .. import nn
from ..block import HybridBlock
from ..loss import Loss
from .bert import BERTPositionwiseFFN, MultiHeadAttention

__all__ = ["Transformer", "TransformerEncoder", "TransformerDecoder",
           "LabelSmoothedCELoss", "transformer_base", "transformer_big",
           "get_transformer_model"]


def _sinusoid_table(max_len: int, units: int) -> np.ndarray:
    """Vaswani et al. sinusoidal position encoding table."""
    pos = np.arange(max_len)[:, None].astype(np.float64)
    dim = np.arange(units)[None, :].astype(np.float64)
    angle = pos / np.power(10000.0, 2 * (dim // 2) / units)
    table = np.where(dim % 2 == 0, np.sin(angle), np.cos(angle))
    return table.astype(np.float32)


class TransformerEncoderCell(HybridBlock):
    def __init__(self, units, hidden_size, num_heads, dropout=0.0, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.attention = MultiHeadAttention(units, num_heads, dropout,
                                                prefix="attn_")
            self.ln1 = nn.LayerNorm(prefix="ln1_")
            self.ffn = BERTPositionwiseFFN(units, hidden_size, dropout,
                                           activation="relu", prefix="ffn_")
            self.ln2 = nn.LayerNorm(prefix="ln2_")
            self.dropout = nn.Dropout(dropout) if dropout else None

    def hybrid_forward(self, F, x, mask):
        att = self.attention(x, x, mask)
        if self.dropout is not None:
            att = self.dropout(att)
        x = self.ln1(x + att)
        x = self.ln2(x + self.ffn(x))
        return x


class TransformerDecoderCell(HybridBlock):
    def __init__(self, units, hidden_size, num_heads, dropout=0.0, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.self_attention = MultiHeadAttention(
                units, num_heads, dropout, causal=True, prefix="self_attn_")
            self.ln1 = nn.LayerNorm(prefix="ln1_")
            self.cross_attention = MultiHeadAttention(
                units, num_heads, dropout, prefix="cross_attn_")
            self.ln2 = nn.LayerNorm(prefix="ln2_")
            self.ffn = BERTPositionwiseFFN(units, hidden_size, dropout,
                                           activation="relu", prefix="ffn_")
            self.ln3 = nn.LayerNorm(prefix="ln3_")
            self.dropout = nn.Dropout(dropout) if dropout else None

    def hybrid_forward(self, F, x, tgt_mask, mem, mem_mask):
        att = self.self_attention(x, x, tgt_mask)
        if self.dropout is not None:
            att = self.dropout(att)
        x = self.ln1(x + att)
        cross = self.cross_attention(x, mem, mem_mask)
        if self.dropout is not None:
            cross = self.dropout(cross)
        x = self.ln2(x + cross)
        x = self.ln3(x + self.ffn(x))
        return x


class TransformerEncoder(HybridBlock):
    def __init__(self, num_layers=6, units=512, hidden_size=2048,
                 num_heads=8, dropout=0.1, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.layers = nn.HybridSequential(prefix="layers_")
            for i in range(num_layers):
                self.layers.add(TransformerEncoderCell(
                    units, hidden_size, num_heads, dropout,
                    prefix=f"layer{i}_"))

    def hybrid_forward(self, F, x, mask):
        for cell in self.layers._children.values():
            x = cell(x, mask)
        return x


class TransformerDecoder(HybridBlock):
    def __init__(self, num_layers=6, units=512, hidden_size=2048,
                 num_heads=8, dropout=0.1, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.layers = nn.HybridSequential(prefix="layers_")
            for i in range(num_layers):
                self.layers.add(TransformerDecoderCell(
                    units, hidden_size, num_heads, dropout,
                    prefix=f"layer{i}_"))

    def hybrid_forward(self, F, x, tgt_mask, mem, mem_mask):
        for cell in self.layers._children.values():
            x = cell(x, tgt_mask, mem, mem_mask)
        return x


class Transformer(HybridBlock):
    """Encoder-decoder transformer for NMT.

    forward(src, tgt, src_valid, tgt_valid) -> logits (B, S_tgt, vocab).
    Source/target embeddings and the output projection are TIED (shared
    Parameter) when share_embed=True, the transformer-base convention for
    joint BPE vocabularies.
    """

    def __init__(self, src_vocab_size, tgt_vocab_size=None, units=512,
                 hidden_size=2048, num_layers=6, num_heads=8, dropout=0.1,
                 max_length=512, share_embed=True, **kwargs):
        super().__init__(**kwargs)
        tgt_vocab_size = tgt_vocab_size or src_vocab_size
        if share_embed and tgt_vocab_size != src_vocab_size:
            raise MXNetError("share_embed requires equal vocab sizes")
        self._units = units
        self._tgt_vocab_size = tgt_vocab_size
        self._scale = float(np.sqrt(units))
        with self.name_scope():
            self.src_embed = nn.Embedding(src_vocab_size, units,
                                          prefix="src_embed_")
            if share_embed:
                self.tgt_embed = self.src_embed
            else:
                self.tgt_embed = nn.Embedding(tgt_vocab_size, units,
                                              prefix="tgt_embed_")
            self.pos_table = self.params.get_constant(
                "pos_table", _sinusoid_table(max_length, units))
            self.encoder = TransformerEncoder(num_layers, units, hidden_size,
                                              num_heads, dropout,
                                              prefix="enc_")
            self.decoder = TransformerDecoder(num_layers, units, hidden_size,
                                              num_heads, dropout,
                                              prefix="dec_")
            self.dropout = nn.Dropout(dropout) if dropout else None
            # output projection tied to the target embedding
            self.out_proj_bias = self.params.get(
                "out_proj_bias", shape=(tgt_vocab_size,), init="zeros")
            self.tied_weight = self.tgt_embed.weight

    def _embed(self, F, embed, tokens, pos_table):
        x = embed(tokens) * self._scale
        seq_len = tokens.shape[1]
        pos = F.slice_axis(pos_table, axis=0, begin=0, end=seq_len)
        x = F.broadcast_add(x, F.expand_dims(pos, axis=0))
        if self.dropout is not None:
            x = self.dropout(x)
        return x

    def _valid_mask(self, F, tokens, valid_length):
        steps = F._arange_like(tokens, axis=1)
        return F.cast(F.broadcast_lesser(
            F.expand_dims(steps, axis=0),
            F.expand_dims(valid_length, axis=-1)), dtype="float32")

    def hybrid_forward(self, F, src, tgt, src_valid, tgt_valid,
                       pos_table, out_proj_bias, tied_weight):
        src_mask = self._valid_mask(F, src, src_valid)
        tgt_mask = self._valid_mask(F, tgt, tgt_valid)
        enc = self.encoder(self._embed(F, self.src_embed, src, pos_table),
                           src_mask)
        dec = self.decoder(self._embed(F, self.tgt_embed, tgt, pos_table),
                           tgt_mask, enc, src_mask)
        return F.FullyConnected(dec, tied_weight, out_proj_bias,
                                num_hidden=self._tgt_vocab_size,
                                flatten=False)

    # ---- inference stages ------------------------------------------------
    def encode(self, src, src_valid):
        """Run the encoder once; returns (memory, src_mask) for decoding."""
        from ..block import F_ND as F

        pos = self.pos_table.data(src.ctx)
        src_mask = self._valid_mask(F, src, src_valid)
        mem = self.encoder(self._embed(F, self.src_embed, src, pos), src_mask)
        return mem, src_mask

    def decode_logits(self, tgt, tgt_valid, mem, src_mask):
        """Decoder + tied projection over an already-encoded source."""
        from ... import nd
        from ..block import F_ND as F

        pos = self.pos_table.data(tgt.ctx)
        tgt_mask = self._valid_mask(F, tgt, tgt_valid)
        dec = self.decoder(self._embed(F, self.tgt_embed, tgt, pos),
                           tgt_mask, mem, src_mask)
        return nd.FullyConnected(dec, self.tied_weight.data(tgt.ctx),
                                 self.out_proj_bias.data(tgt.ctx),
                                 num_hidden=self._tgt_vocab_size,
                                 flatten=False)

    def greedy_decode(self, src, src_valid, max_len=32, bos_id=1, eos_id=2):
        """Greedy autoregressive decoding.  The source is encoded ONCE;
        the host loop reruns only the decoder, whose jit cache keys on the
        target length (bucketed decoding, the reference pattern).  After a
        sequence emits `eos_id` it keeps emitting `eos_id` (frozen)."""
        import numpy as np

        from ... import nd

        b = src.shape[0]
        mem, src_mask = self.encode(src, src_valid)
        tgt = nd.full((b, 1), bos_id, ctx=src.ctx)
        finished = np.zeros(b, bool)
        for _ in range(max_len - 1):
            tgt_valid = nd.full((b,), tgt.shape[1], ctx=src.ctx)
            logits = self.decode_logits(tgt, tgt_valid, mem, src_mask)
            nxt = logits[:, -1, :].argmax(axis=-1).asnumpy().astype("float32")
            nxt = np.where(finished, float(eos_id), nxt)
            finished |= nxt == eos_id
            tgt = nd.concatenate(
                [tgt, nd.array(nxt[:, None], ctx=src.ctx)], axis=1)
            if finished.all():
                break
        return tgt


class LabelSmoothedCELoss(Loss):
    """Cross entropy with label smoothing (ref: gluonnlp LabelSmoothing +
    Sockeye's smoothed CE — standard transformer training loss)."""

    def __init__(self, smoothing=0.1, axis=-1, weight=None, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._smoothing = smoothing
        self._axis = axis

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        logp = F.log_softmax(pred, axis=self._axis)
        nll = F.pick(logp, label, axis=self._axis) * -1.0
        smooth = F.mean(logp, axis=self._axis) * -1.0
        loss = (1.0 - self._smoothing) * nll + self._smoothing * smooth
        if sample_weight is not None:
            loss = F.broadcast_mul(loss, sample_weight)
        return loss


_TRANSFORMER_SPECS = {
    "transformer_base": dict(units=512, hidden_size=2048, num_layers=6,
                             num_heads=8),
    "transformer_big": dict(units=1024, hidden_size=4096, num_layers=6,
                            num_heads=16),
}


def get_transformer_model(model_name="transformer_base", src_vocab_size=32000,
                          **kwargs):
    if model_name not in _TRANSFORMER_SPECS:
        raise MXNetError(f"unknown transformer {model_name}; have "
                         f"{sorted(_TRANSFORMER_SPECS)}")
    spec = dict(_TRANSFORMER_SPECS[model_name])
    spec.update(kwargs)
    return Transformer(src_vocab_size, **spec)


def transformer_base(**kwargs):
    """Vaswani et al. base config (ref: Sockeye/gluonnlp transformer_base)."""
    return get_transformer_model("transformer_base", **kwargs)


def transformer_big(**kwargs):
    return get_transformer_model("transformer_big", **kwargs)
