"""BucketingModule: dynamic sequence lengths via per-bucket executors
sharing parameters (ref: python/mxnet/module/bucketing_module.py; the
reference's long-sequence mechanism, SURVEY.md §5).

TPU note: each bucket is its own jitted XLA program (recompile-per-bucket,
cached after first use) — exactly the XLA analogue of the reference's
one-executor-per-bucket design.  All buckets share one master parameter
dict; switching buckets loads the latest master into the bucket's
executors.
"""
from __future__ import annotations

import logging
from typing import Callable, Dict, Optional

from ..base import MXNetError
from .base_module import BaseModule
from .module import Module

__all__ = ["BucketingModule"]


class BucketingModule(BaseModule):
    def __init__(self, sym_gen: Callable, default_bucket_key=None,
                 logger=logging, context=None, work_load_list=None,
                 fixed_param_names=None, state_names=None):
        super().__init__(logger=logger)
        if default_bucket_key is None:
            raise MXNetError("please specify default_bucket_key")
        self._sym_gen = sym_gen
        self._default_bucket_key = default_bucket_key
        self._context = context
        self._fixed_param_names = fixed_param_names
        self._buckets: Dict[object, Module] = {}
        self._curr_module: Optional[Module] = None
        self._curr_bucket_key = None

    @property
    def symbol(self):
        assert self.binded
        return self._curr_module.symbol

    @property
    def data_names(self):
        if self.binded:
            return self._curr_module.data_names
        return self._sym_gen(self._default_bucket_key)[1]

    @property
    def output_names(self):
        if self.binded:
            return self._curr_module.output_names
        return self._sym_gen(self._default_bucket_key)[0].list_outputs()

    @property
    def data_shapes(self):
        assert self.binded
        return self._curr_module.data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._curr_module.label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        return self._curr_module.output_shapes

    def _gen_module(self, bucket_key) -> Module:
        sym, data_names, label_names = self._sym_gen(bucket_key)
        return Module(sym, data_names=data_names, label_names=label_names,
                      logger=self.logger, context=self._context,
                      fixed_param_names=self._fixed_param_names)

    # ---- bind / params ---------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if self.binded and not force_rebind:
            return
        self.for_training = for_training
        module = self._gen_module(self._default_bucket_key)
        module.bind(data_shapes, label_shapes, for_training=for_training,
                    inputs_need_grad=inputs_need_grad, grad_req=grad_req)
        self._buckets = {self._default_bucket_key: module}
        self._curr_module = module
        self._curr_bucket_key = self._default_bucket_key
        self.binded = True
        self._grad_req = grad_req
        self._inputs_need_grad = inputs_need_grad

    def switch_bucket(self, bucket_key, data_shapes, label_shapes=None):
        """ref: BucketingModule.switch_bucket — lazily create+bind the
        bucket's module, sharing the master params."""
        assert self.binded, "call bind before switching buckets"
        if bucket_key not in self._buckets:
            module = self._gen_module(bucket_key)
            module.bind(data_shapes, label_shapes,
                        for_training=self.for_training,
                        inputs_need_grad=self._inputs_need_grad,
                        grad_req=self._grad_req)
            # share master param dicts so updates propagate across buckets
            default = self._buckets[self._default_bucket_key]
            module._arg_params = default._arg_params
            module._aux_params = default._aux_params
            module.params_initialized = self.params_initialized
            if self.params_initialized:
                module._exec_group.set_params(module._arg_params,
                                              module._aux_params)
            if self.optimizer_initialized:
                module._optimizer = self._curr_module._optimizer
                module._updater = self._curr_module._updater
                module._kvstore = self._curr_module._kvstore
                module.optimizer_initialized = True
            self._buckets[bucket_key] = module
        prev = self._curr_module
        self._curr_module = self._buckets[bucket_key]
        self._curr_bucket_key = bucket_key
        if prev is not self._curr_module and self.params_initialized:
            # load latest master weights into this bucket's executors
            self._curr_module._exec_group.set_params(
                self._curr_module._arg_params, self._curr_module._aux_params)

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        if self.params_initialized and not force_init:
            return
        assert self.binded
        self._curr_module.init_params(initializer=initializer,
                                      arg_params=arg_params,
                                      aux_params=aux_params,
                                      allow_missing=allow_missing,
                                      force_init=force_init,
                                      allow_extra=allow_extra)
        self.params_initialized = True

    def get_params(self):
        assert self.params_initialized
        return self._curr_module.get_params()

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            return
        self._curr_module.init_optimizer(kvstore, optimizer, optimizer_params,
                                         force_init=force_init)
        for mod in self._buckets.values():
            if mod is not self._curr_module:
                mod._optimizer = self._curr_module._optimizer
                mod._updater = self._curr_module._updater
                mod._kvstore = self._curr_module._kvstore
                mod.optimizer_initialized = True
        self.optimizer_initialized = True

    # ---- execution -------------------------------------------------------
    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        bucket_key = data_batch.bucket_key
        if bucket_key is None:
            bucket_key = self._curr_bucket_key
        self.switch_bucket(bucket_key, data_batch.provide_data,
                           data_batch.provide_label)
        self._curr_module.forward(data_batch, is_train=is_train)

    def backward(self, out_grads=None):
        self._curr_module.backward(out_grads=out_grads)

    def update(self):
        self._curr_module.update()

    def get_outputs(self, merge_multi_context=True):
        return self._curr_module.get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        return self._curr_module.get_input_grads(merge_multi_context)

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        self._curr_module.update_metric(eval_metric, labels)

    def install_monitor(self, mon):
        for mod in self._buckets.values():
            mod.install_monitor(mon)

    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False):
        # save under the default bucket's symbol (reference behavior)
        default = self._buckets[self._default_bucket_key]
        default.save_checkpoint(prefix, epoch, save_optimizer_states)
