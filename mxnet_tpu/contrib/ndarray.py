"""mx.contrib.ndarray — imperative contrib op wrappers
(ref: python/mxnet/ndarray/contrib.py generated namespace)."""
from __future__ import annotations

from ..ndarray import register as _register
from .control_flow import cond, foreach, while_loop  # noqa: F401


def __getattr__(name):
    # '_contrib_' registry alias FIRST, bare name as fallback — the ONE
    # lookup rule for every contrib namespace spelling (nd.contrib.X,
    # mx.contrib.ndarray.X).  Contrib-first so that if a plain op and a
    # distinct contrib op ever share a name, the contrib namespace
    # resolves to the contrib-registered one.
    for cand in (f"_contrib_{name}", name):
        try:
            return _register.lookup(cand)
        except AttributeError:
            continue
    raise AttributeError(
        f"no contrib op {name!r} (tried '_contrib_{name}' too)")
