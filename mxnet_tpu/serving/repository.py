"""ModelRepository: versioned deploy-dir artifacts + executor cache.

Loads `contrib.deploy` artifact directories lazily (import_model on
first use), keeps multiple versions per model name, and AOT-compiles
ONE executable per padded-batch bucket via jax.jit(...).lower().compile()
— `Exported.call` alone re-traces on every invocation, which is exactly
the per-request Python dispatch cost serving exists to amortize.  The
executor cache is keyed by bucket size; hits/misses are counted (the
shape-bucketing tests assert each bucket compiles at most once).

Directory conventions:
    repo.add("mlp", "/path/to/artifact")           # explicit, version 1
    repo.add("mlp", "/path/to/v2", version=2)
    repo.scan("/models")   # /models/<name>/<int-version>/meta.json
"""
from __future__ import annotations

import itertools
import os
import threading
import time
from typing import Dict, List, Optional

from ..analysis import sanitizer as _mxsan
from ..resilience import chaos as _chaos
from ..resilience.breaker import CircuitBreaker
from ..telemetry import instruments as _ins
from ..telemetry import tracing as _tracing
from ..telemetry.mxprof import costs as _costs
from .. import compile_cache as _cc
from ..compile_cache import audit as _ir_audit
from . import ModelNotFound, ServingError
from .metrics import ModelMetrics

__all__ = ["ModelRepository", "_ModelEntry"]

# one mxsan compile-site per entry INSTANCE: a fresh repository
# legitimately rebuilds every bucket — only a rebuild within one
# entry's lifetime means its cache lost an executable
_entry_seq = itertools.count(1)


class _ModelEntry:
    """One (model, version): lazily imported artifact + per-bucket
    AOT-compiled executables."""

    def __init__(self, name: str, version: int, path: str):
        self.name, self.version, self.path = name, version, path
        self.metrics = ModelMetrics(name, version)
        self._lock = threading.Lock()
        # artifact import serializes on its OWN lock (mxflow MX008):
        # a multi-second import_model must never block begin_use/
        # end_use/executable-cache lookups, which share the hot entry
        # lock — a rollover draining an old version used to stall
        # behind a cold import of the new one
        self._import_lock = threading.Lock()
        self._served = None
        # mxsan: every bucket-cache access holds self._lock (reads too
        # — the executable() fast path re-checks under the lock)
        self._executables: Dict[int, object] = _mxsan.track(
            {}, f"serving.repository[{name}/v{version}]._executables")
        self._san_site = (f"serving.bucket:{name}/v{version}"
                          f"#{next(_entry_seq)}")
        self.cache_hits = 0
        self.cache_misses = 0
        # degrade-don't-die: consecutive executor failures open this
        # and the server 503s THIS model while the process serves on
        self.breaker = CircuitBreaker(name, version)
        # zero-downtime rollover bookkeeping: requests hold a use-count
        # from admission to completion; a retired entry (no longer the
        # default after ModelRepository.rollover) releases its artifact
        # + executables when the LAST in-flight request finishes —
        # never under one
        self._inflight = 0
        self._retired = False
        self._program_fp: Optional[str] = None  # lazy content hash

    # ---- rollover lifecycle -------------------------------------------

    def begin_use(self) -> "_ModelEntry":
        """One in-flight request starts on this entry (the server holds
        a use across the request; execute() holds one per launch)."""
        with self._lock:
            self._inflight += 1
        return self

    def end_use(self) -> None:
        with self._lock:
            self._inflight -= 1
            if self._retired and self._inflight == 0:
                self._release_locked()

    def retire(self) -> None:
        """This entry lost the default slot: release its executors as
        soon as the in-flight requests drain (now, if none).  The entry
        stays in the repository — an explicit-version request later
        simply re-imports lazily."""
        with self._lock:
            self._retired = True
            if self._inflight == 0:
                self._release_locked()

    def unretire(self) -> None:
        with self._lock:
            self._retired = False

    @property
    def retired(self) -> bool:
        with self._lock:
            return self._retired

    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    def _release_locked(self) -> None:
        """Drop the imported artifact and every compiled executable
        (caller holds self._lock).  With a persistent compile cache
        configured, a comeback costs a disk load, not a compile."""
        self._served = None
        self._executables.clear()

    # ---- lazy artifact ------------------------------------------------

    @property
    def served(self):
        """The reloaded artifact (contrib.deploy.ServedModel), imported
        on first touch — a repository of many models only pays for the
        ones traffic actually hits."""
        if self._served is None:
            if _chaos._ACTIVE:
                # artifact storage flaking (missing blob, torn read):
                # the error must surface to THIS request and leave the
                # entry importable for the next one
                _chaos.check("serving.artifact")
            with self._import_lock:
                if self._served is None:
                    from ..contrib import deploy

                    # single-flight by design: N racing requests must
                    # pay ONE import, so holding the dedicated
                    # import-only lock across the blocking load is the
                    # point (the hot entry lock stays free)
                    self._served = deploy.import_model(self.path)  # mxlint: disable=MX008
        return self._served

    @property
    def meta(self) -> dict:
        return self.served.meta

    @property
    def dynamic_batch(self) -> bool:
        return bool(self.meta.get("dynamic_batch"))

    def input_specs(self) -> List[dict]:
        """meta["inputs"]: [{"shape": [...], "dtype": ...}] — shape[0]
        is None for a dynamic-batch artifact's batchable inputs."""
        return self.meta["inputs"]

    def fixed_batch(self) -> Optional[int]:
        """The exported batch of a fixed-shape artifact (None when
        dynamic, or when the artifact has no batchable input)."""
        if self.dynamic_batch:
            return None
        sizes = {w["shape"][0] for w in self.input_specs()
                 if len(w["shape"]) >= 1}
        return sizes.pop() if len(sizes) == 1 else None

    def coalescable(self) -> bool:
        """Whether requests may share a launch: every output leaf must
        be batch-major (leading dim = the shared batch), otherwise rows
        cannot be handed back per request.  Answered from the meta's
        recorded output avals when present (so a warm process never
        deserializes the StableHLO just to decide this); legacy
        artifacts fall back to the exported program."""
        fixed = self.fixed_batch()
        if not self.dynamic_batch and fixed is None:
            return False  # batchable inputs disagree on dim0
        outs = self.meta.get("outputs")
        if outs is None:  # pre-"outputs" artifact: needs the program
            outs = [{"shape": list(aval.shape)}
                    for aval in self.served.exported.out_avals]
        for o in outs:
            shape = o["shape"]
            if not shape:
                return False  # scalar output: no rows to split
            d0 = shape[0]
            if isinstance(d0, int):
                # dynamic export: an int leading dim did not come from
                # the symbolic batch; fixed export: must equal it
                if self.dynamic_batch or d0 != fixed:
                    return False
        return True

    def _program_fingerprint(self) -> str:
        """sha256 of the artifact's serialized program — the cheap
        content identity the compile-cache ALIAS key uses (hashing the
        bytes is milliseconds; deserializing them is the dominant
        import cost the alias exists to skip)."""
        fp = getattr(self, "_program_fp", None)
        if fp is None:
            import hashlib

            h = hashlib.sha256()
            with open(os.path.join(self.path, "model.stablehlo"),
                      "rb") as f:
                for chunk in iter(lambda: f.read(1 << 20), b""):
                    h.update(chunk)
            fp = self._program_fp = h.hexdigest()
        return fp

    def allowed_buckets(self, ladder: List[int]) -> List[int]:
        """Clamp the configured ladder to what the artifact can serve:
        a fixed-shape artifact has exactly one executable shape.  A
        fixed artifact whose inputs disagree on dim 0 has NO padded
        buckets at all (empty ladder) — it is still servable, one
        request per launch at the exact exported shapes."""
        fixed = self.fixed_batch()
        if self.dynamic_batch:
            return list(ladder)
        return [] if fixed is None else [fixed]

    # ---- executor cache ----------------------------------------------

    def executable(self, bucket: int):
        """The AOT-compiled executable for `bucket` padded rows
        (compiled once; later calls hit the cache)."""
        with self._lock:
            fn = self._executables.get(bucket)
            if fn is not None:
                self.cache_hits += 1
                self.metrics.bump("cache_hits")
                return fn
        compiled, origin = self._compile(bucket)  # OUTSIDE the lock
        with self._lock:
            # a concurrent compile of the same bucket may have won;
            # keep the first so "compiles at most once" stays true for
            # the sequential paths the cache counters are asserted on
            fn = self._executables.setdefault(bucket, compiled)
            self.cache_misses += 1
            self.metrics.bump("cache_misses")
        # mxsan keys on the INSERT (losing a by-design concurrent
        # duplicate build must not read as a cache failure); a
        # persistent-cache load is provenance "cache" — a warm restart
        # rebuilding every bucket from disk is not a recompile storm
        _mxsan.record_compile(self._san_site,
                              bucket if fn is compiled else None,
                              provenance="build" if origin == "compiled"
                              else "cache")
        return fn

    def _compile(self, bucket: int):
        t0 = time.perf_counter()
        compiled, origin = self._compile_impl(bucket)
        dt = time.perf_counter() - t0
        # mxprof cost accounting: computed on the executable object, so
        # persistent-cache loads keep their cost metadata too; the
        # artifact's program fingerprint rides beside it (regression
        # attribution: "did the served program change")
        try:
            fp = self._program_fingerprint()
        except OSError:
            fp = None
        _costs.note(f"serving:{self.name}/v{self.version}",
                    f"bucket={bucket}", _costs.executable_cost(compiled),
                    fingerprint=fp)
        if origin == "compiled":
            # always counted, never gated: a compile on the serving
            # path is the silent TPU latency killer — each one must be
            # visible in the next /metrics scrape
            _ins.serving_compile_total(self.name, self.version).inc()
            _ins.serving_compile_seconds(self.name,
                                         self.version).observe(dt)
        _tracing.record_complete(
            "aot-compile" if origin == "compiled" else "aot-cache-load",
            "serving", t0, dt,
            args={"model": self.name, "version": self.version,
                  "bucket": bucket, "origin": origin})
        return compiled, origin

    def _compile_impl(self, bucket: int):
        """(executable, origin) — origin "compiled" means XLA ran;
        "memory"/"disk" mean the persistent compile cache served it."""
        import jax
        import jax.numpy as jnp

        served = self.served
        if not self.dynamic_batch:
            fixed = self.fixed_batch()
            if fixed is not None and bucket != fixed:
                raise ServingError(
                    f"model {self.name!r} v{self.version}: fixed-shape "
                    f"artifact serves batch {fixed}, not {bucket}")
        in_structs = []
        for w in self.input_specs():
            shape = list(w["shape"])
            if len(shape) >= 1:
                shape[0] = bucket if shape[0] is None else shape[0]
            in_structs.append(
                jax.ShapeDtypeStruct(tuple(shape), jnp.dtype(w["dtype"])))
        p_structs = tuple(jax.ShapeDtypeStruct(v.shape, v.dtype)
                          for v in served.param_values)
        key_struct = jax.ShapeDtypeStruct((2,), jnp.uint32)
        in_avals = tuple((tuple(s.shape), str(s.dtype))
                         for s in in_structs)
        p_avals = tuple((tuple(s.shape), str(s.dtype))
                        for s in p_structs)

        cell = {}

        def build_lowered():
            lowered = cell.get("lowered")
            if lowered is None:
                # touching .exported deserializes the StableHLO — the
                # cold path pays it once here, the alias-warm path
                # never does
                exported = served.exported

                def fn(params, key, *xs):
                    return exported.call(params, key, *xs)

                lowered = cell["lowered"] = jax.jit(fn).lower(
                    p_structs, key_struct, *in_structs)
            return lowered

        def text():
            t = cell.get("text")
            if t is None:
                t = cell["text"] = build_lowered().as_text()
            return t

        def compile_fn():
            return build_lowered().compile()

        # mxir program audit (MXNET_IR_AUDIT=1): serving programs are
        # inference-only — donation is never declared here, so MX014
        # stays quiet and the audit watches for replication, precision,
        # collective, and host-transfer hazards in the served program
        _ir_audit.maybe_audit(
            f"serving:{self.name}/v{self.version}/b{bucket}", text)

        # the named identity view compile provenance diffs a miss
        # against — which of program / bucket / avals / params changed.
        # The fingerprint read opens the artifact file: an unreadable
        # artifact (racing rollover cleanup) degrades the provenance
        # component, never the compile — the in-memory exported
        # program can still build.
        try:
            program_fp = self._program_fingerprint()
        except OSError:
            program_fp = None
        components = {"program": program_fp,
                      "bucket": bucket, "avals": in_avals,
                      "params": p_avals}

        if not _cc.enabled():
            from ..telemetry.mxtriage import provenance as _prov

            # record_miss never raises — diagnostics can't break a build
            _prov.record_miss(
                f"serving:{self.name}/v{self.version}",
                _cc.cache_key("serving.bucket",
                              parts=(bucket, in_avals, p_avals),
                              components=components))
            return compile_fn(), "compiled"

        # content-addressed, deliberately name/version-free: the keys
        # are the program + avals, so the same artifact deployed under
        # a new version (rollover) or another name reuses the warmed
        # executable.  The ALIAS key costs a file hash; the full key
        # (built only when the alias misses) costs trace+lower.
        alias = _cc.cache_key(
            "serving.bucket.alias",
            parts=(self._program_fingerprint(), bucket, in_avals,
                   p_avals))

        def full_key():
            return _cc.cache_key(
                "serving.bucket",
                parts=(bucket, in_avals, p_avals),
                program_text=text(),
                components=components)

        return _cc.get_or_compile(
            f"serving:{self.name}/v{self.version}", full_key,
            compile_fn, alias=alias)

    def execute(self, bucket: int, xs, seed: int = 0) -> list:
        """Run one padded batch through the bucket's executable;
        returns the FLAT output leaves (tree-flatten order).  Holds a
        use-count for the launch so a concurrent rollover never
        releases this entry's executors mid-flight."""
        import jax

        self.begin_use()
        try:
            if _chaos._ACTIVE:
                _chaos.check("serving.execute")
            fn = self.executable(bucket)
            key = jax.random.PRNGKey(seed)
            outs = fn(self.served.param_values, key, *xs)
            return list(outs)
        finally:
            self.end_use()

    def warmup(self, ladder: Optional[List[int]] = None) -> None:
        """Compile ahead of traffic: the smallest allowed bucket by
        default (first-request latency otherwise includes a compile).
        Holds a use-count like a request, so a warmup racing a
        rollover that retires this entry still ends with the entry
        released (end_use re-runs the release once the warmup
        finishes)."""
        self.begin_use()
        try:
            buckets = self.allowed_buckets(ladder or [1])
            self.executable(buckets[0])
        finally:
            self.end_use()


class ModelRepository:
    """Name -> version -> _ModelEntry.  Thread-safe; lookups default to
    the latest version unless :meth:`rollover` pinned one."""

    def __init__(self):
        self._lock = threading.Lock()
        # mxsan: every repository access holds self._lock
        self._models: Dict[str, Dict[int, _ModelEntry]] = _mxsan.track(
            {}, "serving.ModelRepository._models")
        # name -> pinned default version (rollover); absent = latest
        self._default: Dict[str, int] = _mxsan.track(
            {}, "serving.ModelRepository._default")
        # serializes whole rollovers (pin + entry transitions): two
        # racing rollovers must not interleave their retire/unretire
        # calls, which would leave the winning default retired
        self._rollover_lock = threading.Lock()

    def add(self, name: str, path: str,
            version: Optional[int] = None) -> int:
        if not os.path.exists(os.path.join(path, "meta.json")):
            raise ServingError(f"{path!r} is not a deploy artifact "
                               f"directory (no meta.json)")
        with self._lock:
            versions = self._models.setdefault(name, {})
            if version is None:
                version = max(versions, default=0) + 1
            if version in versions:
                raise ServingError(
                    f"model {name!r} version {version} already loaded")
            versions[version] = _ModelEntry(name, version, path)
        return version

    def scan(self, root: str) -> List[str]:
        """Load `root/<name>/<int-version>/` artifact dirs; returns the
        names added.  Non-integer or artifact-less subdirs are skipped
        (a models dir often holds stray files)."""
        added = []
        for name in sorted(os.listdir(root)):
            mdir = os.path.join(root, name)
            if not os.path.isdir(mdir):
                continue
            for v in sorted(os.listdir(mdir)):
                vdir = os.path.join(mdir, v)
                if not v.isdigit() or \
                        not os.path.exists(os.path.join(vdir, "meta.json")):
                    continue
                self.add(name, vdir, version=int(v))
                added.append(f"{name}/{v}")
        return added

    def get(self, name: str, version: Optional[int] = None) -> _ModelEntry:
        with self._lock:
            versions = self._models.get(name)
            if not versions:
                raise ModelNotFound(f"unknown model {name!r}; loaded: "
                                    f"{sorted(self._models)}")
            if version is None:
                version = self._default_version_locked(name, versions)
            entry = versions.get(version)
            if entry is None:
                raise ModelNotFound(
                    f"model {name!r} has versions {sorted(versions)}, "
                    f"not {version}")
        return entry

    def _default_version_locked(self, name: str, versions) -> int:
        v = self._default.get(name)
        # a pinned default that was since removed falls back to latest
        return v if v is not None and v in versions else max(versions)

    def default_version(self, name: str) -> int:
        """The version a version-less request serves right now."""
        with self._lock:
            versions = self._models.get(name)
            if not versions:
                raise ModelNotFound(f"unknown model {name!r}")
            return self._default_version_locked(name, versions)

    def rollover(self, name: str, version: Optional[int] = None) -> int:
        """Zero-downtime version swap.  Atomically pins ``version``
        (latest when None) as the default, so every new version-less
        request lands on it — and because it is PINNED, a later
        :meth:`add` of a newer version no longer shifts traffic until
        the next rollover (the stage-then-swap deploy workflow).  Every
        OTHER version keeps serving its in-flight requests on its
        existing executors and releases them (artifact + compiled
        buckets) once the last one finishes; explicit-version requests
        for a retired version still work, re-importing lazily.

        The swap itself is one dict write under the repository lock —
        requests never observe a state with no default.  Rolling *back*
        is the same call with the old version number.  Returns the new
        default version.

        Concurrent rollovers of one repository serialize on a
        dedicated lock so their entry transitions cannot interleave
        (last pin wins, and the entry states always match the final
        pin)."""
        with self._rollover_lock:
            return self._rollover_locked(name, version)

    def _rollover_locked(self, name: str,
                         version: Optional[int]) -> int:
        with self._lock:
            versions = self._models.get(name)
            if not versions:
                raise ModelNotFound(f"unknown model {name!r}; loaded: "
                                    f"{sorted(self._models)}")
            if version is None:
                version = max(versions)
            new = versions.get(version)
            if new is None:
                raise ModelNotFound(
                    f"model {name!r} has versions {sorted(versions)}, "
                    f"not {version}")
            others = [e for v, e in versions.items() if v != version]
            self._default[name] = version
        # entry state transitions OUTSIDE the repository lock (each
        # entry has its own lock; retire may release executors)
        new.unretire()
        for e in others:
            e.retire()
        return version

    def entries(self) -> List[_ModelEntry]:
        with self._lock:
            return [e for vs in self._models.values()
                    for _, e in sorted(vs.items())]

    def models(self) -> Dict[str, List[int]]:
        with self._lock:
            return {n: sorted(vs) for n, vs in self._models.items()}
