"""mxnet_tpu.ndarray (aka mx.nd): NDArray + the generated op namespace.

ref: python/mxnet/ndarray/__init__.py — op functions are synthesized from
the registry (see register.py); NDArray and creation ops are re-exported.
"""
from __future__ import annotations

import jax as _jax
import numpy as _np

from .ndarray import (NDArray, arange, array, concatenate, empty, from_jax,
                      full, ones, stack, wrap_outputs, zeros)
from . import random
from . import sparse
from .sparse import (BaseSparseNDArray, CSRNDArray, RowSparseNDArray,
                     cast_storage)
from . import register as _register
from . import image

__all__ = ["NDArray", "array", "zeros", "ones", "full", "empty", "arange",
           "concatenate", "stack", "from_jax", "random", "waitall", "save",
           "load", "zeros_like", "ones_like", "sparse", "BaseSparseNDArray",
           "CSRNDArray", "RowSparseNDArray", "cast_storage", "maximum",
           "minimum", "power", "modulo", "logical_and", "logical_or",
           "logical_xor", "linspace"]


def waitall():
    """Block until all dispatched work completes (ref: Engine::WaitForAll).

    PjRt executes per-device work in dispatch order, so a trivial
    computation's completion implies all earlier work on that device is done.
    """
    for d in _jax.devices():
        _jax.device_get(_jax.device_put(_np.zeros(()), d))


def save(fname: str, data):
    """Save NDArrays (ref: NDArray::Save, mx.nd.save). See ..serialization."""
    from ..serialization import save_ndarrays

    save_ndarrays(fname, data)


def load(fname: str):
    from ..serialization import load_ndarrays

    return load_ndarrays(fname)


def _scalar_or_elemwise(broadcast_op, scalar_op, rscalar_op=None):
    """ref: python/mxnet/ndarray/ndarray.py _ufunc_helper — dispatch on
    operand kinds (array/array, array/scalar, scalar/array, scalar/
    scalar).  `rscalar_op` is the REVERSED scalar op for non-commutative
    functions (scalar lhs: 2 ** a must not become a ** 2); commutative
    ops omit it and reuse `scalar_op` with the operands exchanged."""
    def fn(lhs, rhs):
        from .register import lookup

        l_nd = isinstance(lhs, NDArray)
        r_nd = isinstance(rhs, NDArray)
        if l_nd and r_nd:
            return lookup(broadcast_op)(lhs, rhs)
        if l_nd:
            return lookup(scalar_op)(lhs, scalar=float(rhs))
        if r_nd:
            return lookup(rscalar_op or scalar_op)(rhs,
                                                   scalar=float(lhs))
        return lookup(scalar_op)(array(_np.asarray([lhs], _np.float32)),
                                 scalar=float(rhs))
    return fn


maximum = _scalar_or_elemwise("broadcast_maximum", "_maximum_scalar")
minimum = _scalar_or_elemwise("broadcast_minimum", "_minimum_scalar")
# same operand-kind dispatch for the remaining module-level binaries the
# reference exposes (ref: ndarray.py power/modulo + logical_* family);
# the non-commutative pair routes a scalar LHS through the _r* ops
power = _scalar_or_elemwise("broadcast_power", "_power_scalar",
                            "_rpower_scalar")
modulo = _scalar_or_elemwise("broadcast_mod", "_mod_scalar",
                             "_rmod_scalar")
logical_and = _scalar_or_elemwise("broadcast_logical_and",
                                  "_logical_and_scalar")
logical_or = _scalar_or_elemwise("broadcast_logical_or",
                                 "_logical_or_scalar")
logical_xor = _scalar_or_elemwise("broadcast_logical_xor",
                                  "_logical_xor_scalar")


def linspace(start, stop, num, endpoint=True, ctx=None, dtype=None):
    """ref: ndarray.py linspace — evenly spaced values as an NDArray."""
    a = _np.linspace(float(start), float(stop), int(num),
                     endpoint=bool(endpoint)).astype(dtype or "float32")
    return array(a, ctx=ctx)


def __getattr__(name: str):
    if name == "contrib":
        # nd.contrib IS mx.contrib.ndarray (one lookup implementation,
        # ref: python/mxnet/ndarray/contrib.py)
        import importlib

        mod = importlib.import_module("..contrib.ndarray", __name__)
        globals()["contrib"] = mod
        return mod
    try:
        return _register.lookup(name)
    except AttributeError:
        raise AttributeError(f"module 'mxnet_tpu.ndarray' has no attribute {name!r}")
