"""mxnet_tpu.resilience — the chaos suite (ISSUE 6 acceptance).

Every test here proves ONE contract: a specific injected fault produces
exactly the designed recovery, and no injection produces zero behavior
change.  The recoveries under test:

  * transient collective/kvstore fault  -> retried within the backoff
    budget, training result bit-equal to the uninjected twin; a
    persistent fault hard-errors with every attempt in the message;
  * preemption mid-epoch                -> checkpoint at the step
    boundary, ``Trainer``+``AutoCheckpoint.resume()`` continues
    BIT-CONSISTENT with an uninterrupted run (params, optimizer state,
    RNG, data position), including onto a smaller replica count;
  * DataLoader worker death             -> a clear ``WorkerDied`` with
    the worker's identity, never a hang or a silent short epoch;
  * serving executor failures           -> transient ones retry inside
    the batch deadline, persistent ones open the per-model circuit
    breaker (503 that model, process and /healthz stay up), a
    half-open probe closes it again;
  * wedged batch at shutdown            -> the drain deadline fails
    queued work loudly instead of hanging forever.
"""
import json
import os
import time
import urllib.request

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd, resilience
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon import nn
from mxnet_tpu.gluon.data import ArrayDataset, DataLoader
from mxnet_tpu.gluon.data.dataloader import WorkerDied
from mxnet_tpu.resilience import chaos, preemption
from mxnet_tpu.resilience.breaker import CircuitBreaker
from mxnet_tpu.resilience.retry import (RetryExhausted, RetryPolicy,
                                        is_transient)
from mxnet_tpu.telemetry import instruments as _ins


@pytest.fixture(autouse=True)
def _clean_resilience_state():
    chaos.reset_stats()
    preemption.clear()
    yield
    preemption.clear()


# ---------------------------------------------------------------------------
# training helpers: tiny deterministic 2-replica data-parallel job
# ---------------------------------------------------------------------------

_CTXS2 = [mx.cpu(0), mx.cpu(1)]


def _make_net(prefix="rnet_", ctxs=_CTXS2, seed=3):
    np.random.seed(seed)
    mx.random.seed(seed)
    net = nn.Dense(4, in_units=6, prefix=prefix)
    net.initialize(ctx=list(ctxs))
    return net


def _batches(n=6, rows=8):
    rng = np.random.RandomState(0)
    return [(rng.rand(rows, 6).astype("f4"),
             rng.rand(rows, 4).astype("f4")) for _ in range(n)]


def _one_step(net, trainer, xb, yb, ctxs):
    """One data-parallel step: each replica takes its half-batch."""
    half = len(xb) // len(ctxs) if len(ctxs) > 1 else len(xb)
    losses = []
    with autograd.record():
        for r, c in enumerate(ctxs):
            xs = nd.array(xb[r * half:(r + 1) * half] if len(ctxs) > 1
                          else xb, ctx=c)
            ys = nd.array(yb[r * half:(r + 1) * half] if len(ctxs) > 1
                          else yb, ctx=c)
            losses.append(((net(xs) - ys) ** 2).sum())
    for l in losses:
        l.backward()
    trainer.step(len(xb))


def _params_np(net):
    return {p.name: p.list_data()[0].asnumpy().copy()
            for p in net.collect_params().values()}


# ---------------------------------------------------------------------------
# chaos harness basics: disabled fast path, scoping, env-spec grammar
# ---------------------------------------------------------------------------

class TestChaosHarness:
    def test_disabled_path_is_inert_and_training_unchanged(self):
        assert chaos._ACTIVE is False
        data = _batches(2)
        net_a = _make_net("inert_a_")
        tr_a = mx.gluon.Trainer(net_a.collect_params(), "sgd",
                                {"learning_rate": 0.1, "momentum": 0.9})
        for xb, yb in data:
            _one_step(net_a, tr_a, xb, yb, _CTXS2)
        # chaos was never consulted: no site counters exist at all
        assert chaos.stats() == {}

        # entering AND exiting a scope restores the inert state, and a
        # run with a no-op plan (at=999) is bit-identical
        net_b = _make_net("inert_b_")
        tr_b = mx.gluon.Trainer(net_b.collect_params(), "sgd",
                                {"learning_rate": 0.1, "momentum": 0.9})
        with chaos.inject("kvstore.pushpull", at=999):
            assert chaos._ACTIVE is True
            for xb, yb in data:
                _one_step(net_b, tr_b, xb, yb, _CTXS2)
        assert chaos._ACTIVE is False
        a, b = _params_np(net_a), _params_np(net_b)
        for (na, va), (nb, vb) in zip(sorted(a.items()),
                                      sorted(b.items())):
            np.testing.assert_array_equal(va, vb)

    def test_injection_scope_exits_on_exception(self):
        with pytest.raises(chaos.FaultInjected):
            with chaos.inject("dist.collective", times=99):
                chaos.check("dist.collective")
        assert chaos._ACTIVE is False

    def test_env_spec_grammar(self):
        plans = chaos._parse_spec(
            "trainer.preempt@4, serving.execute@x3,"
            "dist.collective@p0.5:hang, dataloader.worker@2", seed=7)
        assert [p.kind for p in plans] == [
            "trainer.preempt", "serving.execute", "dist.collective",
            "dataloader.worker"]
        assert plans[0].action == "preempt" and plans[0].at == 4
        assert plans[1].action == "error" and plans[1].times == 3
        assert plans[2].action == "hang" and plans[2].p == 0.5
        assert plans[3].action == "die" and plans[3].at == 2
        with pytest.raises(MXNetError):
            chaos._parse_spec("no-selector-here", seed=0)

    def test_resilience_errors_survive_pickling(self):
        """Process-pool workers deliver exceptions through a pickle
        pipe; a custom-args __init__ without a __reduce__ kills the
        parent's result handler with TypeError instead — the consumer
        would hang to the full timeout rather than see the fault."""
        import pickle

        e = pickle.loads(pickle.dumps(chaos.FaultInjected("k", 3)))
        assert e.kind == "k" and e.nth == 3 and e.transient
        r = pickle.loads(pickle.dumps(RetryExhausted(
            "s", [chaos.FaultInjected("k", 1), ValueError("x")])))
        assert r.site == "s" and r.attempts == 2
        assert "attempt 2" in str(r)

    def test_fault_counter_telemetry(self):
        before = _ins.fault_injected_total("dist.collective").value
        with chaos.inject("dist.collective", at=1):
            with pytest.raises(chaos.FaultInjected):
                chaos.check("dist.collective")
        assert _ins.fault_injected_total("dist.collective").value \
            == before + 1


# ---------------------------------------------------------------------------
# retry policy
# ---------------------------------------------------------------------------

class TestRetryPolicy:
    def test_transient_classification(self):
        assert is_transient(chaos.FaultInjected("x", 1))
        assert not is_transient(ValueError("boom"))
        assert is_transient(OSError("flake"), retry_on=(OSError,))

    def test_retries_then_succeeds_and_counts(self):
        pol = RetryPolicy(max_attempts=3, base_s=0.001, max_s=0.002,
                          budget_s=5.0)
        calls = []
        before = _ins.retry_total("t.site").value

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise chaos.FaultInjected("t", len(calls))
            return "ok"

        assert pol.call(flaky, site="t.site") == "ok"
        assert len(calls) == 3
        assert _ins.retry_total("t.site").value == before + 2

    def test_non_transient_raises_immediately(self):
        pol = RetryPolicy(max_attempts=5, base_s=0.001, budget_s=5.0)
        calls = []

        def broken():
            calls.append(1)
            raise ValueError("deterministic bug")

        with pytest.raises(ValueError):
            pol.call(broken, site="t.site2")
        assert len(calls) == 1

    def test_exhaustion_reports_every_attempt(self):
        pol = RetryPolicy(max_attempts=2, base_s=0.001, max_s=0.002,
                          budget_s=5.0)
        with pytest.raises(RetryExhausted) as ei:
            pol.call(lambda: (_ for _ in ()).throw(
                chaos.FaultInjected("t", 0)), site="t.site3")
        assert ei.value.attempts == 2
        assert "attempt 1" in str(ei.value)
        assert "attempt 2" in str(ei.value)

    def test_budget_and_deadline_cut_retries_short(self):
        pol = RetryPolicy(max_attempts=50, base_s=0.2, max_s=0.2,
                          budget_s=0.05)

        def always():
            raise chaos.FaultInjected("t", 0)

        t0 = time.monotonic()
        with pytest.raises(RetryExhausted) as ei:
            pol.call(always, site="t.budget")
        assert time.monotonic() - t0 < 1.0
        assert ei.value.attempts == 1  # first backoff already over budget

        pol2 = RetryPolicy(max_attempts=50, base_s=0.2, max_s=0.2,
                           budget_s=30.0)
        with pytest.raises(RetryExhausted):
            pol2.call(always, site="t.deadline",
                      deadline=time.monotonic() + 0.05)


# ---------------------------------------------------------------------------
# collective / kvstore fault injection
# ---------------------------------------------------------------------------

class TestCollectiveFaults:
    def test_injected_kvstore_fault_is_retried_bit_equal(self):
        data = _batches(3)
        net_a = _make_net("kv_a_")
        tr_a = mx.gluon.Trainer(net_a.collect_params(), "sgd",
                                {"learning_rate": 0.05, "momentum": 0.9})
        for xb, yb in data:
            _one_step(net_a, tr_a, xb, yb, _CTXS2)

        net_b = _make_net("kv_b_")
        tr_b = mx.gluon.Trainer(net_b.collect_params(), "sgd",
                                {"learning_rate": 0.05, "momentum": 0.9})
        chaos.reset_stats()
        retries_before = _ins.retry_total("kvstore.pushpull_fused").value
        with chaos.inject("kvstore.pushpull", at=2) as scope:
            for xb, yb in data:
                _one_step(net_b, tr_b, xb, yb, _CTXS2)
            assert scope.fired == 1
        assert chaos.stats()["kvstore.pushpull"]["injected"] == 1
        assert _ins.retry_total("kvstore.pushpull_fused").value \
            == retries_before + 1
        for (na, va), (nb, vb) in zip(sorted(_params_np(net_a).items()),
                                      sorted(_params_np(net_b).items())):
            np.testing.assert_array_equal(va, vb)

    def test_persistent_collective_fault_hard_errors_with_trail(self,
                                                                monkeypatch):
        from mxnet_tpu.parallel import dist
        from mxnet_tpu.resilience import retry as retry_mod

        # fast policy for the test: 2 attempts, ~ms backoff
        monkeypatch.setattr(
            retry_mod, "_DEFAULT",
            RetryPolicy(max_attempts=2, base_s=0.001, max_s=0.002,
                        budget_s=5.0))
        v = nd.array(np.ones((3,), "f4"))
        with chaos.inject("dist.collective", times=99):
            with pytest.raises(RetryExhausted) as ei:
                dist.allreduce_nd(v)
        assert ei.value.attempts == 2
        assert "attempt 2" in str(ei.value)

    def test_single_process_collective_retry_succeeds(self):
        from mxnet_tpu.parallel import dist

        v = nd.array(np.arange(4, dtype="f4"))
        with chaos.inject("dist.collective", at=1):
            out = dist.allreduce_nd(v)  # retried, then the no-op path
        np.testing.assert_array_equal(out.asnumpy(), v.asnumpy())
        assert chaos.stats()["dist.collective"]["injected"] == 1

    def test_injected_hang_trips_the_real_watchdog(self, monkeypatch):
        """The chaos probe runs INSIDE the watchdog window: a `hang`
        plan must stall the collective like a dead peer and fire the
        real timeout machinery (watchdog error + sequence poisoning),
        not sleep outside it and then succeed."""
        from mxnet_tpu.parallel import dist

        monkeypatch.setattr(dist, "_POISONED", None)
        try:
            with chaos.inject("dist.collective", at=1, action="hang",
                              duration=5.0):
                with pytest.raises(MXNetError, match="timed out"):
                    dist._resilient(lambda: 42, timeout=0.2,
                                    what="t", site="t.hang")
            # the blown timeout poisoned the sequence, as a real dead
            # peer would — further collectives refuse
            with pytest.raises(MXNetError, match="refused"):
                dist._run_with_watchdog(lambda: 1, 0.2, "t2")
        finally:
            monkeypatch.setattr(dist, "_POISONED", None)

    def test_kvstore_bucket_retry_engages_without_chaos(self):
        """The retry contract holds in PRODUCTION: a transient-marked
        infra failure in a bucket reduce retries with chaos fully
        disabled, not only under injection."""
        from mxnet_tpu import kvstore as kvs

        assert chaos._ACTIVE is False
        store = kvs.create("device")
        g0 = nd.array(np.ones((4,), "f4"))
        g1 = nd.array(np.ones((4,), "f4") * 2)
        store.init(0, g0)
        real = kvs.KVStore._bucket_allreduce
        fails = []

        class _Blip(MXNetError):
            transient = True

        def flaky(self, *a, **kw):
            if not fails:
                fails.append(1)
                raise _Blip("transient infra blip")
            return real(self, *a, **kw)

        before = _ins.retry_total("kvstore.pushpull_fused").value
        try:
            kvs.KVStore._bucket_allreduce = flaky
            store.pushpull_fused([0], [[g0, g1]], out=[[g0, g1]])
        finally:
            kvs.KVStore._bucket_allreduce = real
        np.testing.assert_array_equal(g0.asnumpy(),
                                      np.full((4,), 3.0, "f4"))
        assert fails == [1]
        assert _ins.retry_total("kvstore.pushpull_fused").value \
            == before + 1


# ---------------------------------------------------------------------------
# preemption-safe training: checkpoint, resume, bit-consistency
# ---------------------------------------------------------------------------

class TestPreemptionResume:
    def test_preempt_resume_is_bit_consistent(self, tmp_path):
        data = _batches(6)

        # run A: never interrupted
        net_a = _make_net("pre_a_")
        tr_a = mx.gluon.Trainer(net_a.collect_params(), "sgd",
                                {"learning_rate": 0.05, "momentum": 0.9})
        for xb, yb in data:
            _one_step(net_a, tr_a, xb, yb, _CTXS2)
        final_a = _params_np(net_a)

        # run B: preempted during step 4, auto-checkpointed, resumed
        net_b = _make_net("pre_b_")
        tr_b = mx.gluon.Trainer(net_b.collect_params(), "sgd",
                                {"learning_rate": 0.05, "momentum": 0.9})
        cursor = [0]
        ck = resilience.AutoCheckpoint(
            str(tmp_path / "ck"), tr_b, every_n_steps=2,
            state_provider=lambda: {"next_batch": cursor[0]})
        with chaos.inject("trainer.preempt", at=4):
            with pytest.raises(resilience.Preempted) as ei:
                for i, (xb, yb) in enumerate(data):
                    # position BEFORE step(): the checkpoint is cut
                    # inside it, and must record where to resume once
                    # THIS batch's update has committed
                    cursor[0] = i + 1
                    _one_step(net_b, tr_b, xb, yb, _CTXS2)
        assert ei.value.checkpoint_dir is not None
        assert os.path.isdir(ei.value.checkpoint_dir)

        # fresh process stand-in: new net (same param names), new
        # trainer, restore, continue from the recorded data position
        net_c = _make_net("pre_b_", seed=99)  # different init on purpose
        tr_c = mx.gluon.Trainer(net_c.collect_params(), "sgd",
                                {"learning_rate": 0.05, "momentum": 0.9})
        ck2 = resilience.AutoCheckpoint(str(tmp_path / "ck"), tr_c)
        meta = ck2.resume()
        assert meta["step"] == 4
        assert meta["position"] == {"next_batch": 4}
        for xb, yb in data[meta["position"]["next_batch"]:]:
            _one_step(net_c, tr_c, xb, yb, _CTXS2)
        final_c = _params_np(net_c)
        assert set(final_a.keys()) == {
            k.replace("pre_b_", "pre_a_") for k in final_c}
        for name_c, vc in sorted(final_c.items()):
            va = final_a[name_c.replace("pre_b_", "pre_a_")]
            np.testing.assert_array_equal(va, vc)

    def test_resume_onto_smaller_replica_count(self, tmp_path):
        data = _batches(3)
        net2 = _make_net("small_")
        tr2 = mx.gluon.Trainer(net2.collect_params(), "sgd",
                               {"learning_rate": 0.05, "momentum": 0.9})
        ck = resilience.AutoCheckpoint(str(tmp_path / "ck"), tr2)
        for xb, yb in data:
            _one_step(net2, tr2, xb, yb, _CTXS2)
        ck.save(sync=True)
        want = _params_np(net2)
        mom2 = [np.asarray(s.asnumpy()) for s in
                tr2._updaters[0].states[0]] \
            if hasattr(tr2._updaters[0], "states") else None

        # "the slice came back smaller": 1 replica instead of 2
        net1 = _make_net("small_", ctxs=[mx.cpu(0)], seed=42)
        tr1 = mx.gluon.Trainer(net1.collect_params(), "sgd",
                               {"learning_rate": 0.05, "momentum": 0.9})
        ck1 = resilience.AutoCheckpoint(str(tmp_path / "ck"), tr1)
        meta = ck1.resume()
        assert meta["step"] == 3
        for name, v in _params_np(net1).items():
            np.testing.assert_array_equal(v, want[name])
        # and it trains on: the restored momentum drives the next step
        _one_step(net1, tr1, *data[0], [mx.cpu(0)])
        assert len(tr1._updaters) == 1

    def test_rng_stream_snapshot_roundtrip(self):
        from mxnet_tpu.resource import resource_manager

        rm = resource_manager()
        mx.random.seed(1234)
        _ = rm.random(mx.cpu(0)).next_key()
        state = rm.rng_state()
        a = np.asarray(rm.random(mx.cpu(0)).next_key())
        rm.set_rng_state(state)
        b = np.asarray(rm.random(mx.cpu(0)).next_key())
        np.testing.assert_array_equal(a, b)
        # and the snapshot is JSON-able (it rides meta.json)
        json.dumps(state)

    def test_atomic_writes_and_keep_last_pruning(self, tmp_path):
        d = str(tmp_path / "ck")
        net = _make_net("prune_")
        tr = mx.gluon.Trainer(net.collect_params(), "sgd",
                              {"learning_rate": 0.05})
        ck = resilience.AutoCheckpoint(d, tr, every_n_steps=1,
                                       keep_last=2)
        for xb, yb in _batches(5):
            _one_step(net, tr, xb, yb, _CTXS2)
        ck.flush()
        names = sorted(os.listdir(d))
        assert names == ["step-00000004", "step-00000005"]
        assert not any(n.startswith(".tmp-") for n in names)

        # a crashed writer's leftover .tmp dir must not confuse resume
        os.makedirs(os.path.join(d, ".tmp-step-00000009"))
        assert resilience.latest_step_dir(d).endswith("step-00000005")

    def test_preemption_save_happens_at_step_boundary(self, tmp_path):
        net = _make_net("bound_")
        tr = mx.gluon.Trainer(net.collect_params(), "sgd",
                              {"learning_rate": 0.05})
        ck = resilience.AutoCheckpoint(str(tmp_path / "ck"), tr)
        xb, yb = _batches(1)[0]
        _one_step(net, tr, xb, yb, _CTXS2)
        preemption.trigger(reason="test")
        with pytest.raises(resilience.Preempted):
            _one_step(net, tr, xb, yb, _CTXS2)
        # the step that observed the signal COMPLETED, then saved
        assert ck.step == 2
        meta = json.load(open(os.path.join(
            resilience.latest_step_dir(str(tmp_path / "ck")),
            "meta.json")))
        assert meta["step"] == 2


# ---------------------------------------------------------------------------
# DataLoader worker death
# ---------------------------------------------------------------------------

class TestWorkerDeath:
    def _ds(self):
        x = np.arange(48, dtype="f4").reshape(12, 4)
        y = np.arange(12, dtype="i4")
        return ArrayDataset(x, y)

    def test_thread_worker_death_raises_workerdied_fast(self):
        dl = DataLoader(self._ds(), batch_size=2, num_workers=1,
                        timeout=60)
        t0 = time.monotonic()
        with chaos.inject("dataloader.worker", at=2, action="die"):
            with pytest.raises(WorkerDied) as ei:
                for _ in dl:
                    pass
        # detected via liveness, NOT by burning the 60s batch timeout
        assert time.monotonic() - t0 < 10
        assert "mx-dataloader-worker-0" in str(ei.value)
        assert ei.value.worker == "mx-dataloader-worker-0"
        # and the loader recovers: a clean epoch right after
        assert sum(1 for _ in dl) == 6

    def test_worker_error_still_propagates_not_workerdied(self):
        class _Bad:
            def __len__(self):
                return 6

            def __getitem__(self, i):
                if i == 3:
                    raise RuntimeError("decode failed")
                return np.zeros((4,), "f4")

        dl = DataLoader(_Bad(), batch_size=2, num_workers=1, timeout=60)
        with pytest.raises(RuntimeError, match="decode failed"):
            for _ in dl:
                pass

    def test_resume_from_skips_without_building(self):
        calls = []

        class _Tracking:
            def __len__(self):
                return 12

            def __getitem__(self, i):
                calls.append(i)
                return np.full((4,), i, "f4")

        dl = DataLoader(_Tracking(), batch_size=2, num_workers=0)
        dl.resume_from(4)
        out = [b.asnumpy()[0, 0] for b in dl]
        assert out == [8.0, 10.0]
        assert min(calls) == 8  # skipped batches were never built
        # one-shot: the next epoch is full again
        assert sum(1 for _ in dl) == 6


@pytest.mark.slow  # spawn pool + per-child jax import ≈ 8s; the
# thread-pool twin above keeps WorkerDied in tier-1, and the nightly
# resilience stage (tools/run_nightly.py) runs this lane
class TestWorkerDeathProcessPool:
    def test_process_worker_death_raises_workerdied_with_pid(self):
        x = np.arange(48, dtype="f4").reshape(12, 4)
        dl = DataLoader(ArrayDataset(x, np.arange(12, dtype="i4")),
                        batch_size=2, num_workers=1,
                        worker_pool="process", timeout=120)
        with chaos.inject("dataloader.worker", at=2, action="die"):
            with pytest.raises(WorkerDied) as ei:
                for _ in dl:
                    pass
        assert isinstance(ei.value.worker, int)  # the child pid
        # the poisoned pool was discarded; a fresh epoch works
        assert sum(1 for _ in dl) == 6

    def test_process_worker_injected_error_crosses_the_pickle_pipe(self):
        """action='error' inside a spawn child: the FaultInjected must
        arrive in the consumer AS FaultInjected (it rides the pool's
        pickle pipe — the __reduce__ regression), not hang the parent
        or surface as a pickling TypeError."""
        x = np.arange(48, dtype="f4").reshape(12, 4)
        dl = DataLoader(ArrayDataset(x, np.arange(12, dtype="i4")),
                        batch_size=2, num_workers=1,
                        worker_pool="process", timeout=120)
        with chaos.inject("dataloader.worker", at=2, action="error"):
            with pytest.raises(chaos.FaultInjected) as ei:
                for _ in dl:
                    pass
        assert ei.value.kind == "dataloader.worker"
        assert sum(1 for _ in dl) == 6  # pool is still healthy


# ---------------------------------------------------------------------------
# serving: breaker, transient retry, artifact faults, drain deadline
# ---------------------------------------------------------------------------

from mxnet_tpu import serving  # noqa: E402
from mxnet_tpu.contrib import deploy  # noqa: E402


@pytest.fixture(scope="module")
def artifact(tmp_path_factory):
    d = tmp_path_factory.mktemp("resil_art")
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(8, activation="relu", in_units=4),
                nn.Dense(2, in_units=8))
    net.initialize(ctx=mx.cpu())
    x = nd.array(np.random.RandomState(0).rand(4, 4).astype("f4"))
    deploy.export_model(net, str(d), [x], dynamic_batch=True)
    return str(d)


def _x1(seed=0):
    return nd.array(np.random.RandomState(seed).rand(1, 4).astype("f4"))


class TestServingResilience:
    def test_transient_executor_failure_retries_within_deadline(
            self, artifact):
        repo = serving.ModelRepository()
        repo.add("m", artifact)
        srv = serving.InferenceServer(repo, serving.ServingConfig(
            max_batch_size=4, batch_timeout_ms=2.0, execute_retries=3))
        with chaos.inject("serving.execute", at=1):
            y = srv.infer("m", [_x1()], timeout_ms=60000)
        assert y.asnumpy().shape == (1, 2)
        assert chaos.stats()["serving.execute"]["injected"] == 1
        assert repo.get("m").breaker.state() == "closed"
        srv.shutdown()

    def test_breaker_opens_degrades_and_recovers(self, artifact):
        repo = serving.ModelRepository()
        repo.add("m", artifact)
        srv = serving.InferenceServer(repo, serving.ServingConfig(
            max_batch_size=4, batch_timeout_ms=2.0,
            breaker_threshold=2, breaker_cooldown_ms=150.0,
            execute_retries=1))
        entry = repo.get("m")
        srv.infer("m", [_x1()])  # warm compile outside the chaos scope
        with chaos.inject("serving.execute", times=99):
            for i in range(2):
                with pytest.raises(MXNetError):
                    srv.infer("m", [_x1()], timeout_ms=10000)
            assert entry.breaker.state() == "open"
            # while OPEN: instant 503 for this model, executor untouched
            calls_when_open = chaos.stats()["serving.execute"]["calls"]
            with pytest.raises(serving.ModelUnavailable):
                srv.infer("m", [_x1()])
            assert chaos.stats()["serving.execute"]["calls"] \
                == calls_when_open
            assert entry.metrics.value("breaker_rejected") == 1
        # cooldown -> half-open probe -> success closes it
        time.sleep(0.2)
        y = srv.infer("m", [_x1()], timeout_ms=10000)
        assert y.asnumpy().shape == (1, 2)
        assert entry.breaker.state() == "closed"
        srv.shutdown()

    def test_breaker_trip_keeps_healthz_up_and_other_models_serving(
            self, artifact, tmp_path):
        repo = serving.ModelRepository()
        repo.add("sick", artifact)
        repo.add("healthy", artifact)
        srv = serving.InferenceServer(repo, serving.ServingConfig(
            max_batch_size=4, batch_timeout_ms=2.0,
            breaker_threshold=1, breaker_cooldown_ms=60000.0,
            execute_retries=1))
        httpd = serving.serve_http(srv, port=0)
        try:
            port = httpd.server_address[1]
            srv.infer("healthy", [_x1()])  # warm + close its breaker
            with chaos.inject("serving.execute", times=99):
                with pytest.raises(MXNetError):
                    srv.infer("sick", [_x1()], timeout_ms=10000)
            assert repo.get("sick").breaker.state() == "open"
            with pytest.raises(serving.ModelUnavailable):
                srv.infer("sick", [_x1()])
            # the process is fine: healthz 200, the healthy model serves
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/healthz", timeout=10) as r:
                assert r.status == 200
                assert json.loads(r.read())["status"] == "serving"
            assert srv.infer("healthy", [_x1()]).asnumpy().shape == (1, 2)
        finally:
            httpd.shutdown()
            srv.shutdown()

    def test_artifact_load_fault_surfaces_then_recovers(self, artifact,
                                                        tmp_path):
        repo = serving.ModelRepository()
        repo.add("m", artifact)
        srv = serving.InferenceServer(repo)
        with chaos.inject("serving.artifact", at=1):
            with pytest.raises(chaos.FaultInjected):
                srv.infer("m", [_x1()])
        # the entry stayed importable; the next request succeeds
        assert srv.infer("m", [_x1()]).asnumpy().shape == (1, 2)
        srv.shutdown()

    def test_drain_timeout_bounds_shutdown_on_wedged_batch(self,
                                                           artifact):
        repo = serving.ModelRepository()
        repo.add("m", artifact)
        srv = serving.InferenceServer(repo, serving.ServingConfig(
            max_batch_size=1, batch_timeout_ms=1.0))
        srv.infer("m", [_x1()])  # warm so the wedge is the only stall
        entry = repo.get("m")
        orig = entry.execute
        entry.execute = lambda *a, **k: (time.sleep(120), orig(*a, **k))[1]
        fut = srv.submit("m", [_x1()])       # wedges the batcher thread
        time.sleep(0.2)
        queued = srv.submit("m", [_x1(1)])   # stuck behind it
        t0 = time.monotonic()
        srv.shutdown(drain=True, timeout=1.0)
        assert time.monotonic() - t0 < 5.0
        with pytest.raises(serving.ServerClosed):
            queued.result(timeout=5)
        assert entry.metrics.value("drain_timeouts") == 1
        entry.execute = orig

    def test_default_drain_timeout_comes_from_config_knob(self,
                                                          artifact):
        repo = serving.ModelRepository()
        repo.add("m", artifact)
        srv = serving.InferenceServer(repo, serving.ServingConfig(
            drain_timeout_s=0.5))
        t0 = time.monotonic()
        srv.shutdown(drain=True)  # nothing queued: instant either way
        assert time.monotonic() - t0 < 5.0


class TestCircuitBreakerUnit:
    def test_state_machine(self):
        br = CircuitBreaker("u", 1, threshold=2, cooldown_s=0.05)
        assert br.state() == "closed" and br.allow()
        br.record_failure()
        assert br.state() == "closed"  # 1 < threshold
        br.record_failure()
        assert br.state() == "open"
        assert not br.allow()
        time.sleep(0.06)
        assert br.allow()          # the half-open probe
        assert br.state() == "half-open"
        assert not br.allow()      # only ONE probe
        br.record_failure()        # probe failed -> re-open
        assert br.state() == "open"
        time.sleep(0.06)
        assert br.allow()
        br.record_success()        # probe succeeded -> closed
        assert br.state() == "closed"
        assert br.allow()

    def test_success_resets_consecutive_count(self):
        br = CircuitBreaker("u2", 1, threshold=3, cooldown_s=60.0)
        for _ in range(2):
            br.record_failure()
        br.record_success()
        for _ in range(2):
            br.record_failure()
        assert br.state() == "closed"

    def test_would_allow_does_not_consume_probe(self):
        br = CircuitBreaker("u3", 1, threshold=1, cooldown_s=0.01)
        br.record_failure()
        time.sleep(0.02)
        assert br.would_allow() and br.would_allow()
        assert br.state() == "open"  # advisory checks changed nothing
        assert br.allow()            # the real probe
        assert not br.would_allow()
        br.abandon_probe()
        assert br.would_allow()
