"""The goodput ledger: decompose a job's wall-clock into productive
training versus named badput categories.

One invariant rules this module — **closure**:

    productive + sum(badput categories) + unattributed == wall clock

Nothing silently vanishes: every second of elapsed time lands in
exactly one bucket, and whatever the feeds could not attribute is
*visible* as ``unattributed`` instead of being absorbed into a
flattering ratio.  The ledger therefore never lets the attributed
total exceed the wall (every feed is capped against the time that is
actually left), and the snapshot reports the closure error when the
caps had to engage.

Categories (``CATEGORIES``):

  * ``compile``              — XLA compile seconds that landed inside a
                               step (mxprof compile events);
  * ``data_wait``            — seconds the training loop waited on the
                               input pipeline (the data-wait span);
  * ``checkpoint_save``      — step-path-BLOCKING checkpoint save
                               seconds (sync saves, and the snapshot
                               portion of async saves; the daemon
                               writer overlaps training and is metric-
                               recorded but not badput);
  * ``checkpoint_restore``   — restore seconds on resume;
  * ``preemption_recovery``  — SIGTERM observation -> first post-resume
                               step, minus the checkpoint/retry seconds
                               inside that window (they keep their own
                               categories);
  * ``retry_backoff``        — backoff sleeps of the retry policy, with
                               a per-site breakdown;
  * ``comm_stall``           — the communication half of a step (the
                               same comm split the mxprof roofline
                               verdict uses);
  * ``unattributed``         — the remainder (computed, never fed).

Feeds come from the existing seams, not new timers: a flight-recorder
step listener consumes mxprof per-step records (productive / compile /
data_wait / comm_stall), while ``RetryPolicy``, ``AutoCheckpoint`` and
the preemption module call :meth:`GoodputLedger.record_badput` /
the recovery-window hooks with directly measured interval seconds.

Category precedence inside one step record (the double-count guard):
external interval badput that occurred during the step (retry sleeps
inside a collective) is peeled off the record's COMM half first —
those seconds are already in their own category, and a sleep inside a
step can only have happened inside a retry-instrumented collective;
credit beyond the comm half belongs to between-step sleeps (outside
every record's wall) and is discarded rather than peeled off genuine
compute — then ``compile``, then ``data_wait`` rides beside the step
(the record's wall does not include it), then ``comm_stall``, and
only the remainder is productive.  A data-wait second can therefore
never also be counted as comm_stall, and a retry sleep never doubles
as comm time.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from .. import instruments as _ins

__all__ = ["CATEGORIES", "GoodputLedger"]

#: every badput category the ledger can attribute (the docs taxonomy;
#: ``unattributed`` is computed at snapshot time, never fed)
CATEGORIES = (
    "compile", "data_wait", "checkpoint_save", "checkpoint_restore",
    "preemption_recovery", "rank_failure_recovery", "retry_backoff",
    "comm_stall",
)

# comm half of a step record, mirroring the roofline split in
# mxprof/recorder.py: grad-allreduce when present, else the phased
# SPMD collectives, else the host-blocking collective spans
_COMM_PHASES = ("reduce-scatter", "all-gather")


def _record_comm_s(rec: dict) -> float:
    phases = rec.get("phases") or {}
    comm = phases.get("grad-allreduce", 0.0)
    if comm == 0.0:
        comm = sum(phases.get(nm, 0.0) for nm in _COMM_PHASES) \
            or sum((rec.get("collectives") or {}).values())
    return comm


class GoodputLedger:
    """Accumulates the decomposition; all mutation under one lock (the
    feeds are step-scale and interval-scale, never op-scale)."""

    def __init__(self, clock=time.monotonic):
        self._lock = threading.Lock()
        self._clock = clock
        self._t0 = clock()
        self._t0_unix = time.time()
        self._productive = 0.0
        self._steps = 0
        self._badput: Dict[str, float] = {c: 0.0 for c in CATEGORIES}
        self._retry_sites: Dict[str, float] = {}
        # per-thread retry-backoff totals: autockpt deducts the sleeps
        # that happened inside ITS blocking save/restore — which run on
        # the calling thread — and must not see a concurrent daemon
        # writer's sleeps (one small entry per thread that ever slept)
        self._retry_by_thread: Dict[int, float] = {}
        # mxprof record consumption state
        self._last_step = 0
        self._last_consume_mono: Optional[float] = None
        # interval badput recorded since the last record consume that
        # OVERLAPS step wall time (retry sleeps inside a collective) —
        # peeled off the next record so it is not counted twice
        self._overlap_since_consume = 0.0
        # open preemption-recovery window:
        # {"t0": mono, "mark": badput-at-open for the subtracted cats}
        self._recovery: Optional[dict] = None
        # the last CLOSED window: {"category", "seconds", "incident"}
        # — the incident id ties the downtime to its postmortem report
        self._last_recovery: Optional[dict] = None

    # ---- interval feeds ----------------------------------------------

    def record_badput(self, category: str, seconds: float,
                      site: Optional[str] = None,
                      overlaps_step: bool = False) -> None:
        """Attribute ``seconds`` of directly measured wall time to one
        badput category.  ``overlaps_step=True`` marks seconds that may
        fall INSIDE a step's wall (retry sleeps under a collective):
        they are peeled off the next consumed step record so the step
        decomposition cannot count them again."""
        if category not in self._badput:
            raise ValueError(f"unknown badput category {category!r} "
                             f"(known: {CATEGORIES})")
        s = max(0.0, float(seconds))
        if s == 0.0:
            return
        with self._lock:
            self._badput[category] += s
            if category == "retry_backoff":
                if site is not None:
                    self._retry_sites[site] = \
                        self._retry_sites.get(site, 0.0) + s
                tid = threading.get_ident()
                self._retry_by_thread[tid] = \
                    self._retry_by_thread.get(tid, 0.0) + s
            if overlaps_step:
                self._overlap_since_consume += s
        _ins.badput_seconds_total(category).inc(s)

    def consume_overlap(self, seconds: float) -> None:
        """Un-mark ``seconds`` of overlap credit: a caller that already
        subtracted interval badput from its OWN measurement (autockpt
        deducting retry sleeps from a save) tells the ledger those
        seconds did not land inside a step after all."""
        with self._lock:
            self._overlap_since_consume = max(
                0.0, self._overlap_since_consume - max(0.0, seconds))

    def category_seconds(self, category: str) -> float:
        with self._lock:
            return self._badput.get(category, 0.0)

    def retry_backoff_this_thread(self) -> float:
        """Cumulative retry-backoff seconds slept on the CALLING
        thread — the mark/delta autockpt uses so a concurrent daemon
        writer's sleeps are never deducted from a sync save."""
        with self._lock:
            return self._retry_by_thread.get(threading.get_ident(),
                                             0.0)

    def set_record_high_water(self, step: int) -> None:
        """Skip mxprof records at or below ``step``: they closed before
        this ledger's clock started (a fresh ledger on a live recorder
        must not back-attribute the previous job's steps)."""
        with self._lock:
            self._last_step = max(0, int(step))

    # ---- preemption recovery window ----------------------------------

    def _recovery_mark_locked(self) -> float:
        # the categories the recovery window must NOT swallow: they are
        # measured directly and keep their own attribution
        return (self._badput["checkpoint_save"]
                + self._badput["checkpoint_restore"]
                + self._badput["retry_backoff"])

    def open_recovery(self, t0_mono: Optional[float] = None,
                      t0_unix: Optional[float] = None,
                      category: str = "preemption_recovery",
                      incident: Optional[str] = None) -> None:
        """Open a recovery window.  ``t0_mono`` is the trigger instant
        on this process's monotonic clock; a resume in a FRESH process
        passes ``t0_unix`` (the trigger time persisted in the
        checkpoint meta) and the window — and the job wall — extend
        back to it: the downtime between the preempted process and
        this one is exactly what the category exists to expose.
        ``category`` names where the window's seconds land:
        ``preemption_recovery`` (the default) or
        ``rank_failure_recovery`` (mxelastic — a peer died/hung and
        the job restarted around it).  ``incident`` stamps the window
        with the mxblackbox incident id (the postmortem report this
        downtime belongs to) — a later open may still stamp an
        already-open window (the trigger opens it before the resume
        learns the id)."""
        if category not in ("preemption_recovery",
                            "rank_failure_recovery"):
            raise ValueError(
                f"unknown recovery category {category!r}")
        now = self._clock()
        with self._lock:
            if self._recovery is not None:
                # first open wins the clock; the incident stamp is
                # still taken (trigger beats resume, resume knows the
                # incident id)
                if incident and not self._recovery.get("incident"):
                    self._recovery["incident"] = incident
                return
            t0 = t0_mono
            if t0 is None and t0_unix is not None:
                t0 = now - max(0.0, time.time() - float(t0_unix))
            if t0 is None:
                t0 = now
            # never let the window reach back over already-attributed
            # steps: recovery starts no earlier than the last closed
            # step (the step SIGTERM interrupted stays productive)
            if self._last_consume_mono is not None:
                t0 = max(t0, self._last_consume_mono)
            if t0 < self._t0:
                # fresh-process resume: the job conceptually started at
                # the preemption — stretch the wall so the downtime is
                # inside it (closure still holds: it lands in
                # preemption_recovery below)
                self._t0 = t0
                self._t0_unix = min(self._t0_unix,
                                    t0_unix or self._t0_unix)
            self._recovery = {"t0": t0, "cat": category,
                              "mark": self._recovery_mark_locked()}
            if incident:
                self._recovery["incident"] = incident

    def mark_step_entry(self) -> None:
        """Stamp the open recovery window with 'a training step has
        ENTERED' (Trainer/SPMD step-entry hook).  The window does not
        close here — the gluon step's forward/backward siblings ran
        BEFORE Trainer.step, so closing now would overlap the record
        that is about to close — but the stamp caps the close: the
        consume below ends the window at min(step entry, record
        start), so a record whose implied start drifts (gspmd's
        next-boundary close) can never stretch recovery past the
        moment training demonstrably resumed."""
        with self._lock:
            win = self._recovery
            if win is not None and "entered" not in win:
                win["entered"] = self._clock()

    def close_recovery(self, end_mono: Optional[float] = None) -> float:
        """Close the window at ``end_mono`` (default: now).  Returns
        the recovery seconds attributed."""
        now = self._clock() if end_mono is None else end_mono
        with self._lock:
            cat = self._recovery["cat"] if self._recovery is not None \
                else "preemption_recovery"
            before = self._badput[cat]
            self._close_recovery_locked(now)
            return self._badput[cat] - before

    def recovery_open(self) -> bool:
        with self._lock:
            return self._recovery is not None

    # ---- the step-record feed ----------------------------------------

    def consume(self, recorder) -> int:
        """Fold every mxprof record newer than the last consumed one
        into the ledger (the flight-recorder step listener calls this
        after each record closes).  Returns how many were consumed."""
        with self._lock:
            last = self._last_step
        recs = recorder.records_since(last)
        if not recs and recorder.current_step() < last:
            # the recorder was clear()ed/swapped: its step counter
            # restarted below our high-water mark
            with self._lock:
                self._last_step = 0
            recs = recorder.records_since(0)
        if not recs:
            return 0
        now = self._clock()
        with self._lock:
            # re-filter against the CURRENT mark: a snapshot() consume
            # racing the listener's must not fold the same records
            # twice (both read the mark before either advanced it)
            recs = [r for r in recs if r["step"] > self._last_step]
            if not recs:
                return 0
            if self._recovery is not None:
                # first post-resume record: close the window at the
                # step's START (its wall reaches back over the
                # forward/backward siblings) so the step itself stays
                # productive; the step-entry stamp caps it from above
                wall0 = float(recs[0].get("wall_s") or 0.0)
                end = now - wall0
                entered = self._recovery.get("entered")
                if entered is not None:
                    end = min(end, entered)
                self._close_recovery_locked(max(end,
                                                self._recovery["t0"]))
            for rec in recs:
                self._consume_one_locked(rec)
            self._last_step = recs[-1]["step"]
            self._last_consume_mono = now
            wall = now - self._t0
            ratio = (self._productive / wall) if wall > 0 else 0.0
        _ins.job_wall_seconds().set(wall)
        _ins.goodput_ratio().set(ratio)
        return len(recs)

    def _close_recovery_locked(self, end_mono: float) -> None:
        win = self._recovery
        if win is None:
            return
        self._recovery = None
        cat = win.get("cat", "preemption_recovery")
        already = self._recovery_mark_locked() - win["mark"]
        s = max(0.0, (end_mono - win["t0"]) - max(0.0, already))
        self._last_recovery = {"category": cat,
                               "seconds": round(s, 6),
                               "incident": win.get("incident")}
        if s:
            self._badput[cat] += s
            # counter bump under the lock is fine here: instruments'
            # RLock never calls back into the ledger
            _ins.badput_seconds_total(cat).inc(s)

    def _consume_one_locked(self, rec: dict) -> None:
        wall = max(0.0, float(rec.get("wall_s") or 0.0))
        # precedence: (1) peel interval badput already attributed
        # elsewhere OUT OF THE COMM HALF — a retry sleep that fell
        # inside this step's wall can only have slept inside a
        # retry-instrumented collective, so it shows up there; credit
        # beyond the comm half belongs to sleeps BETWEEN steps (their
        # wall is outside every record) and is discarded, never peeled
        # off genuine compute; (2) compile; (3) comm; remainder
        # productive.  data_wait rides BESIDE the wall (the record's
        # wall excludes the between-step wait).
        avail = wall
        comm_raw = max(0.0, _record_comm_s(rec))
        overlap = min(self._overlap_since_consume, comm_raw, avail)
        self._overlap_since_consume = 0.0  # drained: older credit
        # cannot belong to a future step's wall
        avail -= overlap
        compile_s = min(max(0.0, float(rec.get("compile_s") or 0.0)),
                        avail)
        avail -= compile_s
        comm_s = min(comm_raw - overlap, avail)
        avail -= comm_s
        dwait = max(0.0, float(rec.get("data_wait_s") or 0.0))
        self._steps += 1
        self._productive += avail
        if compile_s:
            self._badput["compile"] += compile_s
            _ins.badput_seconds_total("compile").inc(compile_s)
        if comm_s:
            self._badput["comm_stall"] += comm_s
            _ins.badput_seconds_total("comm_stall").inc(comm_s)
        if dwait:
            self._badput["data_wait"] += dwait
            _ins.badput_seconds_total("data_wait").inc(dwait)

    # ---- snapshot -----------------------------------------------------

    def snapshot(self) -> dict:
        """The ledger as one JSON-able dict; closure holds by
        construction (``unattributed`` is the clamped remainder, and
        ``closure.error_s`` exposes any over-attribution instead of
        hiding it)."""
        now = self._clock()
        with self._lock:
            wall = max(0.0, now - self._t0)
            badput = {c: round(v, 6) for c, v in self._badput.items()}
            accounted = self._productive + sum(self._badput.values())
            unattributed = wall - accounted
            ratio = (self._productive / wall) if wall > 0 else 0.0
            out = {
                "started_unix": self._t0_unix,
                "wall_s": round(wall, 6),
                "steps": self._steps,
                "productive_s": round(self._productive, 6),
                "badput_s": badput,
                "retry_backoff_by_site": {
                    k: round(v, 6)
                    for k, v in sorted(self._retry_sites.items())},
                "unattributed_s": round(max(0.0, unattributed), 6),
                "goodput_ratio": round(min(1.0, max(0.0, ratio)), 6),
                "closure": {
                    "accounted_s": round(accounted, 6),
                    "error_s": round(min(0.0, unattributed), 6),
                    "ok": unattributed >= -1e-3 * max(wall, 1.0),
                },
                "recovery_open": self._recovery is not None,
            }
            if self._last_recovery is not None:
                out["last_recovery"] = dict(self._last_recovery)
        _ins.job_wall_seconds().set(wall)
        _ins.goodput_ratio().set(out["goodput_ratio"])
        return out
