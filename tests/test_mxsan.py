"""Tier-1 mxsan gate (ISSUE 5): each seeded concurrency/dispatch bug
must produce EXACTLY ONE violation, its corrected twin must be clean,
and the threaded DataLoader teardown must run clean under the
sanitizer.

Every test uses ``mxsan.scope()`` — a private sanitizer instance — so
seeded violations never leak into a session-wide ``MXNET_SAN=1`` run
(the nightly runs this file under the pytest plugin, which fails any
test that dirties the SESSION instance)."""
import json
import os
import subprocess
import sys
import textwrap
import threading
import time

import pytest

import mxnet_tpu as mx
from mxnet_tpu.analysis import sanitizer as mxsan

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def san():
    with mxsan.scope() as s:
        yield s


def kinds(s):
    return [v.kind for v in s.violations()]


# ---------------------------------------------------------------------------
# detector 1: lock-order graph
# ---------------------------------------------------------------------------

class TestLockOrder:
    def test_seeded_inversion_detected_exactly_once(self, san):
        a, b = threading.Lock(), threading.Lock()

        def ab():
            with a:
                with b:
                    pass

        def ba():
            with b:
                with a:
                    pass

        for fn in (ab, ba, ab, ba):  # repeat: dedupe must hold at one
            t = threading.Thread(target=fn)
            t.start()
            t.join()
        assert kinds(san) == ["lock-order"]
        v = san.violations()[0]
        # the report carries BOTH orders: this acquire + the prior edge
        assert len(v.stacks) >= 2
        assert "this acquire" in "".join(v.stacks)
        assert "prior order" in "".join(v.stacks)

    def test_consistent_order_is_clean(self, san):
        a, b = threading.Lock(), threading.Lock()

        def ab():
            with a:
                with b:
                    pass

        for _ in range(3):
            t = threading.Thread(target=ab)
            t.start()
            t.join()
        assert san.violations() == []

    def test_three_lock_cycle_detected(self, san):
        a, b, c = (threading.Lock() for _ in range(3))

        def seq(x, y):
            with x:
                with y:
                    pass

        for pair in ((a, b), (b, c), (c, a)):
            t = threading.Thread(target=seq, args=pair)
            t.start()
            t.join()
        assert kinds(san) == ["lock-order"]

    def test_gate_locked_inverse_orders_are_serialized_not_cycles(
            self, san):
        # both inner orders only ever run under outer gate G: the
        # inversion cannot deadlock and must not be reported
        g, a, b = (threading.Lock() for _ in range(3))

        def gab():
            with g:
                with a:
                    with b:
                        pass

        def gba():
            with g:
                with b:
                    with a:
                        pass

        for fn in (gab, gba):
            t = threading.Thread(target=fn)
            t.start()
            t.join()
        assert san.violations() == [], "\n".join(
            v.format() for v in san.violations())

    def test_gate_alibi_narrows_when_order_later_taken_ungated(
            self, san):
        # phase 1: both orders under gate g — suppressed (serialized).
        # phase 2: the same inversion WITHOUT g — now a real deadlock
        # risk; the stored gate set must narrow and the cycle fire.
        g, a, b = (threading.Lock() for _ in range(3))

        def run(*locks):
            def body():
                for ls in locks:
                    ls.acquire()
                for ls in reversed(locks):
                    ls.release()
            t = threading.Thread(target=body)
            t.start()
            t.join()

        run(g, a, b)
        run(g, b, a)
        assert san.violations() == []  # gate-serialized
        run(a, b)
        run(b, a)
        assert kinds(san) == ["lock-order"]

    def test_suppress_patterns_drop_matching_violations(self):
        with mxsan.scope(suppress=("seed.site",)) as s:
            mxsan.record_compile("seed.site", key=(1,))
            mxsan.record_compile("seed.site", key=(1,))
            assert s.violations() == []
            mxsan.record_compile("other.site", key=(1,))
            mxsan.record_compile("other.site", key=(1,))
            assert kinds(s) == ["recompile-storm"]

    def test_rlock_reentrancy_no_self_cycle(self, san):
        r = threading.RLock()
        with r:
            with r:
                pass
        assert san.violations() == []

    def test_cross_thread_release_does_not_fabricate_edges(self, san):
        # threading.Lock permits release from another thread (handoff);
        # the owner's held list must drop the entry, or every later
        # acquire by that thread would grow phantom order edges
        a, b = threading.Lock(), threading.Lock()
        a.acquire()  # main thread acquires...

        def release_a():
            a.release()  # ...another thread releases (legal handoff)

        t = threading.Thread(target=release_a)
        t.start()
        t.join()
        with b:  # were `a` still "held", this would record a -> b
            pass

        def ba():
            with b:
                with a:
                    pass

        t = threading.Thread(target=ba)  # b -> a must NOT close a cycle
        t.start()
        t.join()
        assert san.violations() == [], "\n".join(
            v.format() for v in san.violations())

    def test_condition_wait_releases_the_lock_for_ordering(self, san):
        # a consumer parked in cv.wait() does NOT hold the lock: the
        # producer taking (cv, other) must not see an inversion against
        # the consumer's (other, cv) pre-wait order... both orders are
        # consistent here, so the graph stays acyclic
        cv = threading.Condition()
        done = {}

        def producer():
            with cv:
                done["x"] = 1
                cv.notify_all()

        with cv:
            t = threading.Thread(target=producer)
            t.start()
            ok = cv.wait_for(lambda: "x" in done, timeout=5)
        t.join()
        assert ok and san.violations() == []


# ---------------------------------------------------------------------------
# detector 2: Eraser-style lockset races on tracked state
# ---------------------------------------------------------------------------

class TestLockset:
    def _run(self, fn, *argsets):
        for args in argsets:
            t = threading.Thread(target=fn, args=args)
            t.start()
            t.join()

    def test_seeded_unsynchronized_write_detected_exactly_once(self, san):
        cache = mxsan.track({}, "seed.cache")

        def put(k):
            cache[k] = 1  # no lock held: the seeded race

        self._run(put, ("a",), ("b",), ("c",))  # repeats stay at one
        assert kinds(san) == ["lockset-race"]
        assert "seed.cache" in san.violations()[0].message

    def test_guarded_twin_is_clean(self, san):
        lock = threading.Lock()
        cache = mxsan.track({}, "seed.cache.guarded")

        def put(k):
            with lock:
                cache[k] = 1

        self._run(put, ("a",), ("b",), ("c",))
        assert san.violations() == []

    def test_double_checked_reads_allowed_when_annotated(self, san):
        lock = threading.Lock()
        cache = mxsan.track({}, "seed.dc", reads="unlocked-ok")

        def get_or_make(k):
            v = cache.get(k)  # optimistic lock-free read: the idiom
            if v is None:
                with lock:
                    if cache.get(k) is None:
                        cache[k] = object()

        self._run(get_or_make, ("a",), ("b",), ("a",))
        assert san.violations() == []

    def test_unlocked_write_fires_even_with_read_exemption(self, san):
        cache = mxsan.track({}, "seed.dc.bad", reads="unlocked-ok")

        def put(k):
            cache[k] = 1

        self._run(put, ("a",), ("b",))
        assert kinds(san) == ["lockset-race"]

    def test_read_only_sharing_after_init_is_clean(self, san):
        table = mxsan.track({"a": 1, "b": 2}, "seed.readonly")
        got = []

        def read(k):
            got.append(table[k])

        self._run(read, ("a",), ("b",), ("a",))
        assert got == [1, 2, 1] and san.violations() == []

    def test_tracked_containers_keep_semantics(self, san):
        d = mxsan.track({"k": 1}, "sem.d")
        l = mxsan.track([1, 2], "sem.l")
        s = mxsan.track({1}, "sem.s")
        d["x"] = 2
        l.append(3)
        s.add(2)
        assert dict(d) == {"k": 1, "x": 2}
        assert list(l) == [1, 2, 3] and sorted(s) == [1, 2]
        assert mxsan.is_tracked(d) and mxsan.is_tracked(l) \
            and mxsan.is_tracked(s)

    def test_track_is_identity_when_disabled(self):
        if mxsan.enabled():  # session-wide MXNET_SAN=1 run
            pytest.skip("sanitizer enabled for the whole session")
        d = {}
        assert mxsan.track(d, "off") is d


# ---------------------------------------------------------------------------
# detector 3: recompile storms
# ---------------------------------------------------------------------------

class TestRecompile:
    def test_seeded_steady_state_recompile_exactly_once(self, san):
        for _ in range(3):  # repeats stay at one violation
            mxsan.record_compile("seed.site", key=("sig",))
        assert kinds(san) == ["recompile-storm"]
        assert "already-built signature" in san.violations()[0].message

    def test_distinct_signatures_under_warmup_clean(self, san):
        for i in range(5):
            mxsan.record_compile("seed.site.ok", key=(i,))
        assert san.violations() == []

    def test_warmup_budget_storm(self):
        with mxsan.scope(recompile_warmup=3) as s:
            for i in range(4):
                mxsan.record_compile("seed.storm", key=(i,))
            assert kinds(s) == ["recompile-storm"]
            assert "warmup" in s.violations()[0].message

    def test_storm_counts_distinct_signatures_not_raw_builds(self):
        # key=None builds (by-design concurrent losers) and duplicate
        # builds must not push a keyed site over the warmup budget
        with mxsan.scope(recompile_warmup=3) as s:
            for i in range(3):
                mxsan.record_compile("seed.mixed", key=(i,))
            for _ in range(5):
                mxsan.record_compile("seed.mixed", key=None)
            assert s.violations() == []
        # a site that never passes keys falls back to the build count
        with mxsan.scope(recompile_warmup=3) as s:
            for _ in range(4):
                mxsan.record_compile("seed.unkeyed", key=None)
            assert kinds(s) == ["recompile-storm"]

    def test_cache_provenance_is_never_a_storm(self):
        """ISSUE 7: a persistent-compile-cache load (disk or memory
        tier) repeats keys by DESIGN — a warm restart rebuilds every
        executable from the store.  provenance="cache" must feed
        neither the duplicate-key nor the warmup detector, while still
        being tallied for the report."""
        with mxsan.scope(recompile_warmup=3) as s:
            mxsan.record_compile("seed.cache", key=("sig",))
            for _ in range(5):  # warm reloads of the same signature
                mxsan.record_compile("seed.cache", key=("sig",),
                                     provenance="cache")
            assert s.violations() == []
            for i in range(10):  # bulk warm loads: not a storm either
                mxsan.record_compile("seed.cache", key=(i,),
                                     provenance="cache")
            assert s.violations() == []
            rec = s.compile_sites["seed.cache"]
            assert rec["cache_loads"] == 15
            assert rec["count"] == 1  # only the real build counted
            # ...and a REAL duplicate build still fires
            mxsan.record_compile("seed.cache", key=("sig",))
            assert kinds(s) == ["recompile-storm"]

    def test_cache_loads_surface_in_report(self):
        from mxnet_tpu.analysis.sanitizer import report as sreport

        with mxsan.scope() as s:
            mxsan.record_compile("seed.rep", key=(1,))
            mxsan.record_compile("seed.rep", key=(1,),
                                 provenance="cache")
            doc = sreport.render_json(s)
        site = doc["compile_sites"]["seed.rep"]
        assert site["count"] == 1 and site["cache_loads"] == 1

    def test_serving_disk_hit_under_sanitizer_is_clean(self, tmp_path):
        """Integration: rebuild a serving bucket from the persistent
        cache (the eviction/rollover-release path) under an active
        sanitizer — zero violations, and the cache load is visible at
        the entry's compile site."""
        import numpy as np

        from mxnet_tpu import compile_cache as cc
        from mxnet_tpu import nd, serving
        from mxnet_tpu.contrib import deploy
        from mxnet_tpu.gluon import nn

        net = nn.Dense(4, in_units=6, prefix="sanccl_")
        net.initialize(ctx=mx.cpu())
        x = nd.array(np.random.RandomState(0).rand(2, 6).astype("f4"))
        art = str(tmp_path / "art")
        deploy.export_model(net, art, [x], dynamic_batch=True)
        cc.reset(cc.CompileCache(disk_dir=str(tmp_path / "cache")))
        try:
            repo = serving.ModelRepository()
            repo.add("m", art)
            e = repo.get("m")
            with mxsan.scope() as s:
                e.execute(2, [x.data])       # real build
                with e._lock:
                    e._executables.clear()   # simulate release
                e.execute(2, [x.data])       # cache reload, same key
                assert s.violations() == []
                rec = s.compile_sites[e._san_site]
                assert rec["cache_loads"] == 1
        finally:
            cc.reset()

    def test_ops_registry_cache_loss_is_runtime_detected(self, san):
        # ground truth for what MX001 guesses statically: force the jit
        # cache to lose an entry and the SAME signature recompiles
        from mxnet_tpu.ops import registry

        op = registry.get_op("broadcast_add")
        key = registry.freeze_attrs({})
        for _ in range(2):  # evict first: earlier tests may have
            with registry._jit_lock:  # compiled this op already
                registry._jit_cache.pop((op.name, key), None)
            registry.jitted(op, key)
        assert kinds(san) == ["recompile-storm"]
        assert "ops.jit:broadcast_add" in san.violations()[0].message


# ---------------------------------------------------------------------------
# satellite: DataLoader threaded-pool shutdown under the sanitizer
# ---------------------------------------------------------------------------

class TestDataLoaderShutdownUnderSan:
    def _loader(self):
        from mxnet_tpu.gluon.data import DataLoader
        from mxnet_tpu.gluon.data.dataset import ArrayDataset
        import numpy as np

        x = np.arange(64, dtype="float32").reshape(16, 4)
        return DataLoader(ArrayDataset(x), batch_size=4, num_workers=2,
                          worker_pool="thread")

    def test_full_epoch_teardown_clean(self, san):
        loader = self._loader()
        n = sum(1 for _ in loader)
        time.sleep(0.05)  # let worker threads drain their sentinels
        assert n == 4
        assert san.violations() == [], "\n".join(
            v.format() for v in san.violations())

    def test_early_break_teardown_clean(self, san):
        # the regression: done_cv/stop teardown with batches still in
        # flight — no post-stop tracked-state race, no order cycle
        loader = self._loader()
        it = iter(loader)
        next(it)
        it.close()  # triggers the finally: stop.set() + sentinels
        time.sleep(0.05)
        assert san.violations() == [], "\n".join(
            v.format() for v in san.violations())


# ---------------------------------------------------------------------------
# reporting, dedupe, telemetry
# ---------------------------------------------------------------------------

class TestReport:
    def test_json_shape_and_write(self, san, tmp_path):
        cache = mxsan.track({}, "rep.cache")

        def put(k):
            cache[k] = 1

        for a in ("a", "b"):
            t = threading.Thread(target=put, args=(a,))
            t.start()
            t.join()
        mxsan.record_compile("rep.site", key=1)
        doc = mxsan.write_report(str(tmp_path / "MXSAN.json"), san)
        on_disk = json.load(open(tmp_path / "MXSAN.json"))
        assert on_disk["counts"] == doc["counts"]
        assert doc["ok"] is False
        assert doc["counts"]["violations"] == 1
        assert doc["counts"]["lockset-race"] == 1
        assert doc["compile_sites"]["rep.site"]["count"] == 1
        v = doc["violations"][0]
        assert {"kind", "message", "site", "thread", "fingerprint",
                "stacks"} <= set(v)
        assert "FAIL" in mxsan.render_text(san)

    def test_violations_surface_in_telemetry_counter(self, san):
        from mxnet_tpu.telemetry import instruments

        base = instruments.san_violations_total("lockset-race").value
        cache = mxsan.track({}, "tel.cache")

        def put(k):
            cache[k] = 1

        for a in ("a", "b"):
            t = threading.Thread(target=put, args=(a,))
            t.start()
            t.join()
        assert len(san.violations()) == 1
        got = instruments.san_violations_total("lockset-race").value
        assert got == base + 1

    def test_scope_isolates_and_restores(self):
        # under a session-wide MXNET_SAN=1 run `prev` is the session
        # instance and threading stays patched; standalone it is None
        # and the patch must fully unwind
        prev = mxsan.get_active()
        before = threading.Lock
        with mxsan.scope() as s1:
            assert mxsan.get_active() is s1
            with mxsan.scope() as s2:
                assert mxsan.get_active() is s2
                mxsan.record_compile("nested", key=1)
                mxsan.record_compile("nested", key=1)
            assert mxsan.get_active() is s1
            assert s1.violations() == [] and len(s2.violations()) == 1
        assert mxsan.get_active() is prev
        assert threading.Lock is before


# ---------------------------------------------------------------------------
# the pytest plugin + MXNET_SAN knob, end to end (one subprocess)
# ---------------------------------------------------------------------------

@pytest.mark.slow
class TestPluginEndToEnd:
    def test_plugin_fails_dirty_test_and_writes_report(self, tmp_path):
        (tmp_path / "conftest.py").write_text(textwrap.dedent(f"""
            import sys
            sys.path.insert(0, {os.path.join(_REPO, 'tools')!r})
            import mxsan_pytest

            def pytest_configure(config):
                config.pluginmanager.register(
                    mxsan_pytest.MxsanPlugin(), "mxsan")
            """))
        (tmp_path / "test_seeded.py").write_text(textwrap.dedent("""
            import mxnet_tpu  # MXNET_SAN=1 arms the session sanitizer
            from mxnet_tpu.analysis import sanitizer as mxsan

            def test_dirty():
                mxsan.record_compile("plugin.smoke", key=1)
                mxsan.record_compile("plugin.smoke", key=1)

            def test_clean_after():
                assert mxsan.enabled()
            """))
        out = tmp_path / "MXSAN.json"
        env = dict(os.environ, JAX_PLATFORMS="cpu", MXNET_SAN="1",
                   MXNET_SAN_OUT=str(out))
        p = subprocess.run(
            [sys.executable, "-m", "pytest", str(tmp_path), "-q",
             "-p", "no:cacheprovider"],
            capture_output=True, text=True, timeout=300, cwd=_REPO,
            env=env)
        assert p.returncode != 0, p.stdout[-2000:]
        assert "MxsanViolationError" in p.stdout
        assert "test_dirty" in p.stdout
        # the violation errors the dirty test at teardown (its call
        # phase passed); the clean test after it still passes because
        # the snapshot advances past attributed findings
        assert "1 error" in p.stdout
        assert "2 passed" in p.stdout
        report = json.load(open(out))
        assert report["counts"]["violations"] == 1
        assert report["counts"]["recompile-storm"] == 1
