"""mxflow engine units (ISSUE 8): per-function CFG + dominators +
reaching defs, whole-program call-graph resolution (methods through
the class hierarchy, op-registry indirection, unresolvable-call
conservatism), and the content-hash summary cache's invalidation
behavior.  Pure AST work — the whole module must stay well under the
dataflow tests' 20s budget."""
import ast
import json
import os
import textwrap

import pytest

from mxnet_tpu.analysis import dataflow as df
from mxnet_tpu.analysis.dataflow import cfg as cfg_mod


def _fn(source: str) -> ast.AST:
    tree = ast.parse(textwrap.dedent(source).lstrip("\n"))
    return tree.body[0]


def _stmt_block(g, pred):
    hits = [b for b in g.blocks
            if b.stmt is not None and pred(b.stmt)]
    assert hits, "statement not found in CFG"
    return hits[0]


class TestCFG:
    def test_diamond_dominators(self):
        g = df.build_cfg(_fn("""
            def f(x):
                a = source()
                if a:
                    b = 1
                else:
                    b = 2
                return b
            """))
        dom = df.dominators(g)
        header = _stmt_block(g, lambda s: isinstance(s, ast.If))
        then = _stmt_block(
            g, lambda s: isinstance(s, ast.Assign) and s.lineno == 4)
        other = _stmt_block(
            g, lambda s: isinstance(s, ast.Assign) and s.lineno == 6)
        join = _stmt_block(g, lambda s: isinstance(s, ast.Return))
        # the if-header dominates everything downstream; neither
        # branch dominates the join
        assert header.id in dom[join.id]
        assert then.id not in dom[join.id]
        assert other.id not in dom[join.id]
        # the join postdominates both branches
        pdom = df.postdominators(g)
        assert join.id in pdom[then.id]
        assert join.id in pdom[other.id]

    def test_loop_has_back_edge_and_header_dominates_body(self):
        g = df.build_cfg(_fn("""
            def f(xs):
                total = 0
                for x in xs:
                    total = work(total, x)
                return total
            """))
        header = _stmt_block(g, lambda s: isinstance(s, ast.For))
        body = _stmt_block(
            g, lambda s: isinstance(s, ast.Assign) and s.lineno == 4)
        assert header.id in body.succs  # the back edge
        assert header.id in df.dominators(g)[body.id]

    def test_exception_edges_only_for_raising_statements(self):
        g = df.build_cfg(_fn("""
            def f(entry):
                n = 1
                v = fetch()
                return v + n
            """))
        plain = _stmt_block(
            g, lambda s: isinstance(s, ast.Assign) and s.lineno == 2)
        risky = _stmt_block(
            g, lambda s: isinstance(s, ast.Assign) and s.lineno == 3)
        assert g.raise_id not in plain.succs
        assert g.raise_id in risky.succs

    def test_finally_clones_keep_normal_and_raise_paths_apart(self):
        # the duplication property: a normal completion must not be
        # able to wander into the raise exit just because a finally
        # exists (the single-shared-finally over-approximation)
        g = df.build_cfg(_fn("""
            def f(entry):
                try:
                    v = fetch()
                finally:
                    entry.log()
                return v
            """))
        ret = _stmt_block(g, lambda s: isinstance(s, ast.Return))
        # some finally clone flows to the return (normal), some to the
        # raise exit (exceptional) — but never the same clone to both
        fin_clones = [b for b in g.blocks
                      if b.stmt is not None and b.stmt.lineno == 5]
        assert len(fin_clones) >= 2
        to_ret = [b for b in fin_clones if ret.id in b.succs]
        to_raise = [b for b in fin_clones if g.raise_id in b.succs
                    and ret.id not in b.succs]
        assert to_ret and to_raise

    def test_reaching_defs_kill_and_merge(self):
        g = df.build_cfg(_fn("""
            def f(c):
                x = 1
                if c:
                    x = 2
                y = use(x)
                return y
            """))
        defs = df.reaching_defs(g)
        use_block = _stmt_block(
            g, lambda s: isinstance(s, ast.Assign) and s.lineno == 5)
        x_defs = {d for (n, d) in defs[use_block.id] if n == "x"}
        # both the initial def and the branch redefinition reach the
        # use (the branch may not execute)
        assert len(x_defs) == 2

    def test_can_raise_ignores_nested_defs_and_safe_calls(self):
        assert not cfg_mod.can_raise(ast.parse(
            "def g():\n    boom()\n").body[0])
        assert not cfg_mod.can_raise(ast.parse("n = len(xs)").body[0])
        assert cfg_mod.can_raise(ast.parse("n = fetch(xs)").body[0])
        assert cfg_mod.can_raise(ast.parse("assert x").body[0])


# ---------------------------------------------------------------------------
# call-graph resolution over a real (tmp) package
# ---------------------------------------------------------------------------

@pytest.fixture
def pkg(tmp_path):
    """A small package exercising the resolution features: methods
    through a base class, cross-module imports, register_op
    indirection, and an unresolvable third-party call."""
    root = tmp_path / "tpkg"
    root.mkdir()
    (root / "__init__.py").write_text("")
    (root / "base.py").write_text(textwrap.dedent("""
        class Base:
            def log(self):
                return self._v.asnumpy()
        """))
    (root / "impl.py").write_text(textwrap.dedent("""
        from .base import Base

        class ImplTrainer(Base):
            def step(self, n):
                self.log()          # resolves through the base class
        """))
    (root / "ops.py").write_text(textwrap.dedent("""
        from .registry import register_op

        @register_op("fancy_relu")
        def fancy_relu(x):
            \"\"\"doc\"\"\"
            return x.item()
        """))
    (root / "registry.py").write_text(textwrap.dedent("""
        def register_op(name):
            def wrap(fn):
                return fn
            return wrap
        """))
    (root / "use.py").write_text(textwrap.dedent("""
        import third_party_thing as tp

        def go(F, x):
            return F.fancy_relu(x)   # op-registry indirection

        def mystery(x):
            return tp.who_knows(x)   # unresolvable
        """))
    return root


class TestResolution:
    def test_method_resolves_through_class_hierarchy(self, pkg):
        proj = df.build_project([str(pkg)], use_cache=False)
        step = proj.funcs["tpkg.impl:ImplTrainer.step"]
        [(entry, callees)] = [(e, c) for e, c in step.edges
                              if e["ref"] == ["self", "log"]]
        assert [c.qual for c in callees] == ["tpkg.base:Base.log"]
        # and the transitive fact flows: step reaches the base's sync
        assert step.t_syncs is not None
        assert step.t_syncs[0] == "call"

    def test_op_registry_indirection(self, pkg):
        proj = df.build_project([str(pkg)], use_cache=False)
        assert proj.ops["fancy_relu"] == "tpkg.ops:fancy_relu"
        go = proj.funcs["tpkg.use:go"]
        callees = [c.qual for e, c2 in go.edges for c in c2]
        assert "tpkg.ops:fancy_relu" in callees
        assert go.t_syncs is not None  # .item() two hops away

    def test_unresolvable_call_contributes_nothing(self, pkg):
        proj = df.build_project([str(pkg)], use_cache=False)
        mystery = proj.funcs["tpkg.use:mystery"]
        for entry, callees in mystery.edges:
            assert callees == []
        assert mystery.t_syncs is None and mystery.t_blocks is None

    def test_constructor_call_resolves_to_init(self, tmp_path):
        root = tmp_path / "cpkg"
        root.mkdir()
        (root / "__init__.py").write_text("")
        (root / "store.py").write_text(textwrap.dedent("""
            import os

            class Store:
                def __init__(self, d):
                    os.makedirs(d)
            """))
        (root / "core.py").write_text(textwrap.dedent("""
            from .store import Store

            def build(d):
                return Store(d)
            """))
        proj = df.build_project([str(root)], use_cache=False)
        build = proj.funcs["cpkg.core:build"]
        assert build.t_blocks is not None  # makedirs via __init__
        path, _ = proj.witness_path(build.t_blocks, "blocks")
        assert "makedirs" in path


# ---------------------------------------------------------------------------
# summary cache: content-hash keyed, invalidates on edit
# ---------------------------------------------------------------------------

class TestSummaryCache:
    def _mk(self, tmp_path):
        root = tmp_path / "kpkg"
        root.mkdir()
        (root / "__init__.py").write_text("")
        (root / "a.py").write_text(textwrap.dedent("""
            def helper():
                return 1

            def caller():
                return helper()
            """))
        return root

    def test_second_build_hits_the_cache(self, tmp_path):
        root = self._mk(tmp_path)
        p1 = df.build_project([str(root)])
        assert p1.cache_misses == 2 and p1.cache_hits == 0
        cache_file = tmp_path / df.CACHE_NAME
        assert cache_file.exists()
        p2 = df.build_project([str(root)])
        assert p2.cache_hits == 2 and p2.cache_misses == 0

    def test_editing_a_dependency_invalidates_its_summary(self, tmp_path):
        root = self._mk(tmp_path)
        p1 = df.build_project([str(root)])
        assert p1.funcs["kpkg.a:caller"].t_syncs is None
        # edit the DEPENDENCY: helper now syncs.  caller's own file is
        # untouched, but its transitive fact must change (derived facts
        # are recomputed every build; only local summaries are cached)
        (root / "a.py").write_text(textwrap.dedent("""
            def helper():
                return thing.asnumpy()

            def caller():
                return helper()
            """))
        p2 = df.build_project([str(root)])
        assert p2.cache_misses >= 1  # the edited file re-extracted
        caller = p2.funcs["kpkg.a:caller"]
        assert caller.t_syncs is not None
        path, _ = p2.witness_path(caller.t_syncs, "syncs")
        assert "asnumpy" in path

    def test_cross_file_invalidation(self, tmp_path):
        root = tmp_path / "xpkg"
        root.mkdir()
        (root / "__init__.py").write_text("")
        (root / "util.py").write_text("def h():\n    return 1\n")
        (root / "main.py").write_text(
            "from .util import h\n\ndef top():\n    return h()\n")
        p1 = df.build_project([str(root)])
        assert p1.funcs["xpkg.main:top"].t_blocks is None
        (root / "util.py").write_text(
            "import time\n\ndef h():\n    time.sleep(1)\n")
        p2 = df.build_project([str(root)])
        # main.py came from the cache; its DERIVED fact still updated
        top = p2.funcs["xpkg.main:top"]
        assert top.t_blocks is not None
        path, _ = p2.witness_path(top.t_blocks, "blocks")
        assert "sleep" in path

    def test_corrupt_cache_file_is_tolerated(self, tmp_path):
        root = self._mk(tmp_path)
        df.build_project([str(root)])
        (tmp_path / df.CACHE_NAME).write_text("{definitely not json")
        p = df.build_project([str(root)])
        assert p.cache_misses == 2  # rebuilt from scratch, no crash
        assert "kpkg.a:caller" in p.funcs

    def test_cache_is_versioned_json(self, tmp_path):
        root = self._mk(tmp_path)
        df.build_project([str(root)])
        doc = json.loads((tmp_path / df.CACHE_NAME).read_text())
        assert isinstance(doc["version"], int)
        assert set(doc["files"]) == {"kpkg/__init__.py", "kpkg/a.py"}
        for ent in doc["files"].values():
            assert len(ent["sha1"]) == 40


class TestPragmaAwareSummaries:
    def test_pragma_on_effect_line_kills_the_chain(self, tmp_path):
        root = tmp_path / "ppkg"
        root.mkdir()
        (root / "__init__.py").write_text("")
        (root / "m.py").write_text(textwrap.dedent("""
            def blessed():
                return x.asnumpy()  # mxlint: disable=MX002

            def flagged():
                return y.asnumpy()
            """))
        proj = df.build_project([str(root)], use_cache=False)
        assert proj.funcs["ppkg.m:blessed"].t_syncs is None
        assert proj.funcs["ppkg.m:flagged"].t_syncs is not None
