"""mxnet_tpu.serving — dynamic-batching inference serving on top of the
StableHLO deploy path (contrib/deploy.py).

The deploy story ends at `ServedModel`: one Python call, one request,
re-traced dispatch every time.  This package is the production serving
substrate above it:

  * `ModelRepository` — loads/versions multiple deploy-dir artifacts
    (reusing `contrib.deploy.import_model`), lazily, with per-bucket
    AOT-compiled executables and an executor cache (hit/miss counters);
  * `DynamicBatcher` — coalesces concurrent single-sample requests into
    padded, shape-bucketed batches so each bucket hits ONE cached
    compiled executable instead of paying per-request Python dispatch
    (the Julia-to-TPU lesson: whole-program XLA makes dispatch the
    bottleneck — amortize it server-side);
  * `InferenceServer` — threaded, stdlib-only front end with a bounded
    admission queue, per-request deadlines, backpressure
    (reject-with-503 semantics instead of unbounded queueing), and
    graceful drain on shutdown;
  * per-model metrics (QPS, p50/p99 latency, batch occupancy, queue
    depth, rejections) on the `mxnet_tpu.telemetry` registry — one
    Prometheus scrape (`GET /metrics` on the HTTP front end) sees every
    model plus AOT-compile counters; `GET /healthz` is drain-aware
    (200 serving / 503 draining); the `dumps()`-style JSON snapshot is
    unchanged; with tracing on, each request carries one trace id
    linking admission→queue-wait→batch-assembly→execute→respond spans.

Quick start:

    from mxnet_tpu import serving
    repo = serving.ModelRepository()
    repo.add("mlp", "deploy_dir")            # a contrib.deploy artifact
    server = serving.InferenceServer(
        repo, serving.ServingConfig(max_batch_size=32,
                                    batch_timeout_ms=2.0))
    y = server.infer("mlp", [x])             # single blocking call
    fut = server.submit("mlp", [x])          # concurrent path
    print(server.dumps())                    # metrics snapshot (JSON)
    server.shutdown(drain=True)

See docs/serving.md for artifact layout, batching knobs, backpressure
semantics, and the metrics snapshot format.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

from ..base import MXNetError

__all__ = [
    "ServingError", "ServerOverloaded", "DeadlineExceeded",
    "ServerClosed", "ModelNotFound", "ModelUnavailable",
    "ServingConfig", "ModelRepository", "DynamicBatcher",
    "InferenceServer", "serve_http",
]


class ServingError(MXNetError):
    """Base class for serving failures; `status` maps to HTTP."""

    status = 500


class ServerOverloaded(ServingError):
    """Admission queue full — the 503 backpressure signal.  Clients
    should back off and retry; the server never queues unboundedly."""

    status = 503


class DeadlineExceeded(ServingError):
    """The request's deadline expired before execution (504)."""

    status = 504


class ServerClosed(ServingError):
    """Submitted after shutdown began (503; drain rejects new work)."""

    status = 503


class ModelNotFound(ServingError):
    """No such model name or version in the repository (404 — a client
    routing mistake, not a server fault)."""

    status = 404


class ModelUnavailable(ServingError):
    """This model's circuit breaker is OPEN: its executor failed
    `breaker_threshold` consecutive times, so requests for it answer
    503 until a half-open probe succeeds.  Other models — and the
    process, and /healthz — are unaffected: degrade, don't die."""

    status = 503


def default_bucket_ladder(max_batch_size: int) -> List[int]:
    """Powers of two up to max_batch_size (always included): each
    distinct padded batch size is one compiled executable, so the
    ladder trades compile count against padding waste."""
    ladder, b = [], 1
    while b < max_batch_size:
        ladder.append(b)
        b *= 2
    ladder.append(max_batch_size)
    return ladder


@dataclass
class ServingConfig:
    """Batching/admission knobs (one config serves every model; the
    bucket ladder is clamped per-model to what its artifact allows).

    max_batch_size    — coalesce at most this many rows per executable
                        launch (fixed-shape artifacts clamp this to
                        their exported batch).
    batch_timeout_ms  — a non-full batch launches once its oldest
                        request has waited this long (latency bound).
    buckets           — explicit padded-batch ladder; default is powers
                        of two up to max_batch_size.
    max_queue         — bound on admitted-but-incomplete requests per
                        server; beyond it submits fail ServerOverloaded.
    default_timeout_ms — per-request deadline when the caller gives
                        none; None = no deadline.
    drain_timeout_s   — hard deadline for shutdown(drain=True): past it
                        still-queued requests fail with ServerClosed
                        instead of the shutdown hanging on a wedged
                        batch.  None = the MXNET_DRAIN_TIMEOUT_MS knob.
    breaker_threshold / breaker_cooldown_ms — per-model circuit-breaker
                        overrides (None = the MXNET_BREAKER_* knobs).
    execute_retries   — max attempts for a TRANSIENT executor failure
                        within a batch launch (deadline-aware); None =
                        the MXNET_RETRY_MAX_ATTEMPTS knob.
    """

    max_batch_size: int = 32
    batch_timeout_ms: float = 5.0
    buckets: Optional[List[int]] = None
    max_queue: int = 256
    default_timeout_ms: Optional[float] = None
    drain_timeout_s: Optional[float] = None
    breaker_threshold: Optional[int] = None
    breaker_cooldown_ms: Optional[float] = None
    execute_retries: Optional[int] = None

    def ladder(self) -> List[int]:
        if self.buckets:
            lad = sorted(set(int(b) for b in self.buckets))
            if lad[0] < 1:
                raise ServingError(f"bucket ladder {lad}: sizes must "
                                   f"be >= 1")
            return lad
        return default_bucket_ladder(self.max_batch_size)


from .repository import ModelRepository  # noqa: E402
from .batcher import DynamicBatcher  # noqa: E402
from .server import InferenceServer  # noqa: E402
from .http import serve_http  # noqa: E402
