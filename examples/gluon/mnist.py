"""MNIST MLP — the canonical minimum end-to-end workload
(ref: example/gluon/mnist.py; BASELINE.md config 1).

Usage:  python examples/gluon/mnist.py [--epochs N] [--cpu] [--hybridize]
"""
import argparse
import time

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.gluon import nn


def build_net():
    net = nn.HybridSequential()
    net.add(nn.Dense(128, activation="relu"),
            nn.Dense(64, activation="relu"),
            nn.Dense(10))
    return net


def transformer(img, label):
    return img.astype("float32").reshape((-1,)) / 255.0, label


def run(epochs=5, ctx=None, hybridize=True, batch_size=100, lr=0.1):
    ctx = ctx or (mx.tpu() if mx.num_tpus() else mx.cpu())
    train_data = gluon.data.DataLoader(
        gluon.data.vision.MNIST(train=True).transform(transformer),
        batch_size=batch_size, shuffle=True, last_batch="discard")
    val_data = gluon.data.DataLoader(
        gluon.data.vision.MNIST(train=False).transform(transformer),
        batch_size=batch_size, shuffle=False)

    net = build_net()
    net.initialize(mx.initializer.Xavier(magnitude=2.24), ctx=ctx)
    if hybridize:
        net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": lr, "momentum": 0.9})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    metric = mx.metric.Accuracy()

    for epoch in range(epochs):
        metric.reset()
        tic = time.time()
        n = 0
        for data, label in train_data:
            data = data.as_in_context(ctx)
            label = label.as_in_context(ctx)
            with autograd.record():
                output = net(data)
                loss = loss_fn(output, label)
            loss.backward()
            trainer.step(data.shape[0])
            metric.update([label], [output])
            n += data.shape[0]
        name, acc = metric.get()
        print(f"[epoch {epoch}] {name}={acc:.4f} "
              f"({n / (time.time() - tic):.0f} samples/s)")

    metric.reset()
    for data, label in val_data:
        output = net(data.as_in_context(ctx))
        metric.update([label.as_in_context(ctx)], [output])
    name, acc = metric.get()
    print(f"[val] {name}={acc:.4f}")
    return acc


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=5)
    p.add_argument("--batch-size", type=int, default=100)
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--cpu", action="store_true")
    p.add_argument("--no-hybridize", action="store_true")
    args = p.parse_args()
    acc = run(args.epochs, mx.cpu() if args.cpu else None,
              not args.no_hybridize, args.batch_size, args.lr)
    assert acc > 0.9, f"val accuracy too low: {acc}"
