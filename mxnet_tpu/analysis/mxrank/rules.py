"""mxrank rules (MX019–MX020): cross-rank collective-schedule
verification, the static half of the mxrank invariant (the runtime
half is ``parallel/schedule.py``'s fingerprint ledger).

Both rules ride the mxflow project index for *scope* — a function is
checked when it is hot (the Trainer/Updater/KVStore step chain),
reachable from a hot function through the resolved call graph, or
lives under ``parallel/`` (the collective layer itself); serving is
out of scope — and the mxrank taint lattice (``taint.py``) for the
finding itself: a rank-/data-tainted predicate whose paths issue
different collective multisets.

Same precision-over-recall policy as MX008–MX012: an unresolvable
call contributes nothing, and a finding needs BOTH the tainted
predicate AND asymmetric collectives — rank-gated logging or
checkpointing never fires.
"""
from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set

from ..engine import FileContext, Rule, Violation, register_rule
# NOTE `from ..dataflow import X` (one level into the sibling package),
# never `from ..dataflow.rules import X`: the two-level form walks the
# import from the ROOT package and breaks the CLI's standalone
# (jax-free) load — see analysis/__init__.
from ..dataflow import Project, get_project
from .taint import DATA, RANK, Divergence, ModuleTaint, taint_names

__all__ = ["RankDivergentSchedule", "DataDivergentSchedule"]


def _reachable_from_hot(proj: Project) -> Set[str]:
    """Quals reachable from the step chain via resolved call edges."""
    seen: Set[str] = set()
    work = [f for f in proj.funcs.values() if f.hot]
    seen.update(f.qual for f in work)
    while work:
        fn = work.pop()
        for _entry, callees in fn.edges:
            for g in callees:
                if g.qual not in seen:
                    seen.add(g.qual)
                    work.append(g)
    return seen


def _parallel_mod(mod: str) -> bool:
    return "parallel" in mod.split(".")


def _serving_mod(mod: str) -> bool:
    return "serving" in mod.split(".")


class _MxrankRule(Rule):
    """Base: record every FileContext, share the project in
    finalize(), run the module taint walk once per file."""

    def __init__(self) -> None:
        self._ctxs: List[FileContext] = []

    def check(self, ctx: FileContext) -> Iterable[Violation]:
        self._ctxs.append(ctx)
        return ()

    def finalize(self) -> Iterable[Violation]:
        if not self._ctxs:
            return ()
        proj = get_project(self._ctxs)
        hot_reach = _reachable_from_hot(proj)
        out: List[Violation] = []
        for ctx in self._ctxs:
            mod = proj.path_mod.get(ctx.path)
            if mod is None or _serving_mod(mod):
                continue
            try:
                mt = ModuleTaint(ctx.tree)
            except SyntaxError:
                continue
            in_parallel = _parallel_mod(mod)
            for name, cls, node in mt.functions():
                qual = f"{mod}:{cls}.{name}" if cls else f"{mod}:{name}"
                fi = proj.funcs.get(qual)
                if fi is None:
                    continue
                if not (fi.hot or in_parallel or qual in hot_reach):
                    continue
                for d in mt.analyze(name, cls, node):
                    if not self._selects(d):
                        continue
                    v = ctx.violation(self.id, d.node, self._message(d))
                    if not ctx.suppressed(self.id, v.line):
                        out.append(v)
        return out

    def _selects(self, d: Divergence) -> bool:
        raise NotImplementedError

    def _message(self, d: Divergence) -> str:
        raise NotImplementedError


@register_rule
class RankDivergentSchedule(_MxrankRule):
    """MX019: a collective call site reachable under a rank-tainted
    branch where the sibling path issues a different collective
    multiset.  Rank 0 enters a reduce rank 1 never issues; the job
    hangs until the watchdog fires and — without the runtime ledger —
    is misclassified as a peer failure and replayed forever."""

    id = "MX019"
    name = "rank-divergent-schedule"
    description = ("Collective schedule depends on rank identity: a "
                   "branch on rank()/process_index()/rank-env state "
                   "where the two paths issue different collective "
                   "multisets — ranks deadlock in the collective.")

    def _selects(self, d: Divergence) -> bool:
        return bool(d.taint & RANK)

    def _message(self, d: Divergence) -> str:
        return (f"collective schedule diverges across ranks: "
                f"{d.describe()} under a "
                f"{taint_names(d.taint)}-tainted predicate — every "
                "rank must issue the identical collective sequence; "
                "hoist the collective out of the rank conditional "
                "(keep only non-collective work rank-gated).")


@register_rule
class DataDivergentSchedule(_MxrankRule):
    """MX020: collective order/count depends on a data-tainted
    predicate (loss scalar, nonfinite count, batch contents) that was
    not first made globally consistent.  Each rank sees different
    data, so ranks take different branches and the schedules drift.
    The clean pattern is the mxhealth ``skip_step`` idiom: all-reduce
    the predicate, then branch — which this rule recognizes by
    construction (a collective's result carries no taint)."""

    id = "MX020"
    name = "data-divergent-schedule"
    description = ("Collective order/count depends on a data-tainted "
                   "predicate (loss/nonfinite/batch) without an "
                   "enclosing all-reduce of that predicate — ranks "
                   "see different data and desynchronize.")

    def _selects(self, d: Divergence) -> bool:
        # pure data taint; rank-tainted predicates are MX019's finding
        return bool(d.taint & DATA) and not (d.taint & RANK)

    def _message(self, d: Divergence) -> str:
        return (f"collective schedule depends on per-rank data: "
                f"{d.describe()} under a data-tainted predicate — "
                "all-reduce the predicate first (the mxhealth "
                "skip_step idiom) so every rank takes the same "
                "branch, then branch on the globally consistent "
                "result.")
