"""Recurrent cells (ref: python/mxnet/gluon/rnn/rnn_cell.py):
RecurrentCell base (state_info/begin_state/unroll), RNNCell, LSTMCell,
GRUCell, SequentialRNNCell, BidirectionalCell, DropoutCell, ZoneoutCell,
ResidualCell."""
from __future__ import annotations

from ...base import MXNetError
from ..block import HybridBlock
from ..parameter import Parameter

__all__ = ["RecurrentCell", "HybridRecurrentCell", "RNNCell", "LSTMCell",
           "GRUCell", "SequentialRNNCell", "HybridSequentialRNNCell",
           "BidirectionalCell", "DropoutCell", "ZoneoutCell", "ResidualCell"]


def _cells_state_info(cells, batch_size):
    return sum([c.state_info(batch_size) for c in cells], [])


def _cells_begin_state(cells, **kwargs):
    return sum([c.begin_state(**kwargs) for c in cells], [])


def _format_sequence(length, inputs, layout, merge):
    """Normalise inputs to a list of per-step tensors or a merged tensor."""
    from ... import ndarray as nd
    from ...ndarray.ndarray import NDArray

    axis = layout.find("T")
    batch_axis = layout.find("N")
    if isinstance(inputs, (list, tuple)):
        seq = list(inputs)
        if merge:
            merged = nd.stack(*seq, axis=axis) if isinstance(seq[0], NDArray) \
                else _jstack(seq, axis)
            return merged, axis, batch_axis
        return seq, axis, batch_axis
    # tensor input
    if merge:
        return inputs, axis, batch_axis
    if isinstance(inputs, NDArray):
        steps = nd.split(inputs, num_outputs=inputs.shape[axis], axis=axis,
                         squeeze_axis=True)
        if inputs.shape[axis] == 1:
            steps = [steps] if isinstance(steps, NDArray) else steps
        return list(steps), axis, batch_axis
    import jax.numpy as jnp

    return [jnp.squeeze(s, axis=axis)
            for s in jnp.split(inputs, inputs.shape[axis], axis=axis)], \
        axis, batch_axis


def _jstack(seq, axis):
    import jax.numpy as jnp

    return jnp.stack(seq, axis=axis)


class RecurrentCell(HybridBlock):
    def __init__(self, prefix=None, params=None):
        super().__init__(prefix, params)
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1
        for c in self._children.values():
            if hasattr(c, "reset"):
                c.reset()

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, ctx=None, **kwargs):
        from ... import ndarray as nd

        if func is None:
            func = nd.zeros
        states = []
        for info in self.state_info(batch_size):
            self._init_counter += 1
            states.append(func(tuple(info["shape"]), ctx=ctx, **kwargs))
        return states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        """Explicit unroll (ref: rnn_cell.py::unroll). Under hybridize the
        whole unroll is traced into one XLA program."""
        self.reset()
        inputs_list, axis, batch_axis = _format_sequence(
            length, inputs, layout, False)
        if begin_state is None:
            bs = inputs_list[0].shape[batch_axis] if batch_axis < 1 else \
                inputs_list[0].shape[0]
            begin_state = self.begin_state(batch_size=bs,
                                           ctx=getattr(inputs_list[0], "ctx", None))
        states = begin_state
        outputs = []
        for i in range(length):
            output, states = self(inputs_list[i], states)
            outputs.append(output)
        if valid_length is not None:
            from ... import ndarray as nd

            stacked = nd.stack(*outputs, axis=axis)
            stacked = nd.sequence_mask(stacked, valid_length,
                                       use_sequence_length=True, axis=axis)
            if merge_outputs is False:
                outputs = nd.split(stacked, num_outputs=length, axis=axis,
                                   squeeze_axis=True)
            else:
                outputs = stacked
            return outputs, states
        if merge_outputs:
            from ... import ndarray as nd

            outputs = nd.stack(*outputs, axis=axis)
        return outputs, states

    def forward(self, inputs, states):
        self._counter += 1
        return super().forward(inputs, states)

    def _alias(self):
        return "rnn"


HybridRecurrentCell = RecurrentCell


class RNNCell(RecurrentCell):
    def __init__(self, hidden_size, activation="tanh", i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None,
                 params=None):
        super().__init__(prefix, params)
        self._hidden_size = hidden_size
        self._activation = activation
        self._input_size = input_size
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(hidden_size, input_size),
                init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight", shape=(hidden_size, hidden_size),
                init=h2h_weight_initializer)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(hidden_size,), init=i2h_bias_initializer)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(hidden_size,), init=h2h_bias_initializer)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _alias(self):
        return "rnn"

    def _infer_param_shapes(self, x, *args):
        self.i2h_weight.shape = (self._hidden_size, int(x.shape[-1]))

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=self._hidden_size)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=self._hidden_size)
        output = F.Activation(i2h + h2h, act_type=self._activation)
        return output, [output]


class LSTMCell(RecurrentCell):
    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None,
                 params=None, activation="tanh", recurrent_activation="sigmoid"):
        super().__init__(prefix, params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(4 * hidden_size, input_size),
                init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight", shape=(4 * hidden_size, hidden_size),
                init=h2h_weight_initializer)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(4 * hidden_size,),
                init=i2h_bias_initializer)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(4 * hidden_size,),
                init=h2h_bias_initializer)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _alias(self):
        return "lstm"

    def _infer_param_shapes(self, x, *args):
        self.i2h_weight.shape = (4 * self._hidden_size, int(x.shape[-1]))

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=4 * self._hidden_size)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=4 * self._hidden_size)
        gates = i2h + h2h
        slices = F.split(gates, num_outputs=4, axis=1)
        i = F.sigmoid(slices[0])
        f = F.sigmoid(slices[1])
        g = F.tanh(slices[2])
        o = F.sigmoid(slices[3])
        c = f * states[1] + i * g
        h = o * F.tanh(c)
        return h, [h, c]


class GRUCell(RecurrentCell):
    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None,
                 params=None):
        super().__init__(prefix, params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(3 * hidden_size, input_size),
                init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight", shape=(3 * hidden_size, hidden_size),
                init=h2h_weight_initializer)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(3 * hidden_size,),
                init=i2h_bias_initializer)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(3 * hidden_size,),
                init=h2h_bias_initializer)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _alias(self):
        return "gru"

    def _infer_param_shapes(self, x, *args):
        self.i2h_weight.shape = (3 * self._hidden_size, int(x.shape[-1]))

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        prev = states[0]
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=3 * self._hidden_size)
        h2h = F.FullyConnected(prev, h2h_weight, h2h_bias,
                               num_hidden=3 * self._hidden_size)
        i2h_r, i2h_z, i2h_n = F.split(i2h, num_outputs=3, axis=1)
        h2h_r, h2h_z, h2h_n = F.split(h2h, num_outputs=3, axis=1)
        r = F.sigmoid(i2h_r + h2h_r)
        z = F.sigmoid(i2h_z + h2h_z)
        n = F.tanh(i2h_n + r * h2h_n)
        h = (1 - z) * n + z * prev
        return h, [h]


class SequentialRNNCell(RecurrentCell):
    def __init__(self, prefix=None, params=None):
        super().__init__(prefix, params)

    def add(self, cell):
        self.register_child(cell)

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children.values(), batch_size)

    def begin_state(self, **kwargs):
        return _cells_begin_state(self._children.values(), **kwargs)

    def __len__(self):
        return len(self._children)

    def __getitem__(self, i):
        return list(self._children.values())[i]

    def hybrid_forward(self, F, inputs, states):
        next_states = []
        p = 0
        for cell in self._children.values():
            n = len(cell.state_info())
            state = states[p:p + n]
            p += n
            inputs, state = cell(inputs, state)
            next_states.extend(state)
        return inputs, next_states


HybridSequentialRNNCell = SequentialRNNCell


class DropoutCell(RecurrentCell):
    def __init__(self, rate, axes=(), prefix=None, params=None):
        super().__init__(prefix, params)
        self._rate = rate
        self._axes = axes

    def state_info(self, batch_size=0):
        return []

    def hybrid_forward(self, F, inputs, states):
        if self._rate > 0:
            inputs = F.Dropout(inputs, p=self._rate, axes=self._axes)
        return inputs, states


class ModifierCell(RecurrentCell):
    def __init__(self, base_cell):
        super().__init__(prefix=base_cell.prefix + self._alias() + "_",
                         params=None)
        self.base_cell = base_cell

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, **kwargs):
        return self.base_cell.begin_state(**kwargs)


class ZoneoutCell(ModifierCell):
    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self._prev_output = None

    def _alias(self):
        return "zoneout"

    def hybrid_forward(self, F, inputs, states):
        next_output, next_states = self.base_cell(inputs, states)
        if self.zoneout_outputs > 0:
            mask = F.Dropout(F.ones_like(next_output), p=self.zoneout_outputs)
            prev = self._prev_output if self._prev_output is not None \
                else F.zeros_like(next_output)
            next_output = F.where(mask, next_output, prev)
        if self.zoneout_states > 0:
            new_states = []
            for ns, s in zip(next_states, states):
                mask = F.Dropout(F.ones_like(ns), p=self.zoneout_states)
                new_states.append(F.where(mask, ns, s))
            next_states = new_states
        self._prev_output = next_output
        return next_output, next_states


class ResidualCell(ModifierCell):
    def _alias(self):
        return "residual"

    def hybrid_forward(self, F, inputs, states):
        output, states = self.base_cell(inputs, states)
        return output + inputs, states


class BidirectionalCell(RecurrentCell):
    def __init__(self, l_cell, r_cell, output_prefix="bi_"):
        super().__init__(prefix="", params=None)
        self.register_child(l_cell, "l_cell")
        self.register_child(r_cell, "r_cell")
        self._output_prefix = output_prefix

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children.values(), batch_size)

    def begin_state(self, **kwargs):
        return _cells_begin_state(self._children.values(), **kwargs)

    def __call__(self, inputs, states):
        raise MXNetError("BidirectionalCell cannot be stepped; use unroll()")

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        from ... import ndarray as nd

        self.reset()
        inputs_list, axis, batch_axis = _format_sequence(length, inputs,
                                                         layout, False)
        bs = inputs_list[0].shape[batch_axis - 1 if axis < batch_axis else batch_axis]
        if begin_state is None:
            begin_state = self.begin_state(
                batch_size=inputs_list[0].shape[0],
                ctx=getattr(inputs_list[0], "ctx", None))
        l_cell, r_cell = self._children.values()
        n_l = len(l_cell.state_info())
        l_out, l_states = l_cell.unroll(length, inputs_list,
                                        begin_state[:n_l], layout, False,
                                        valid_length)
        rev_inputs = list(reversed(inputs_list))
        r_out, r_states = r_cell.unroll(length, rev_inputs,
                                        begin_state[n_l:], layout, False,
                                        valid_length)
        r_out = list(reversed(r_out))
        outputs = [nd.concat(l, r, dim=1) for l, r in zip(l_out, r_out)]
        if merge_outputs:
            outputs = nd.stack(*outputs, axis=axis)
        return outputs, l_states + r_states
