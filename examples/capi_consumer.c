/* Standalone C consumer of the minimal NDArray/op C ABI.
 *
 * The counterpart of the reference's cpp-package "hello world"
 * (ref: cpp-package/example + include/mxnet/c_api.h): no Python on the
 * consumer side — MXCapiInit() embeds a CPython interpreter (the
 * framework's runtime) into this process and every later call marshals
 * through it.  Any of the 423 registered operators can be invoked by
 * name with reference-style string attrs.
 *
 * Build & run (from the repo root; the .so is built on demand by
 * `python -c "from mxnet_tpu import lib; lib.capi_get()"`):
 *
 *   gcc examples/capi_consumer.c -o /tmp/capi_demo \
 *       build/libmxnet_tpu_capi.so \
 *       -L"$(python -c 'import sysconfig; print(sysconfig.get_config_var("LIBDIR"))')" \
 *       -lpython3.12 \
 *       -Wl,-rpath,"$(python -c 'import sysconfig; print(sysconfig.get_config_var("LIBDIR"))')" \
 *       -Wl,-rpath,"$PWD/build"
 *   PYTHONPATH=$PWD /tmp/capi_demo
 *
 * (`tests/test_capi.py::test_standalone_c_consumer` compiles and runs
 * this same flow in CI.)
 */
#include <stdint.h>
#include <stdio.h>

extern int MXCapiInit(void);
extern const char* MXCapiGetLastError(void);
extern int MXNDArrayCreate(const int64_t* shape, int ndim,
                           const char* dtype, void** out);
extern int MXNDArrayFree(void* h);
extern int MXNDArraySyncCopyFromCPU(void* h, const void* data,
                                    uint64_t nbytes);
extern int MXNDArraySyncCopyToCPU(void* h, void* data, uint64_t nbytes);
extern int MXNDArrayGetShape(void* h, int* ndim, int64_t* shape,
                             int max_ndim);
extern int MXImperativeInvoke(const char* op, void** inputs, int nin,
                              const char** keys, const char** vals,
                              int nparams, void** outputs, int* nout,
                              int max_out);

#define CHECK(call)                                       \
  do {                                                    \
    if ((call) != 0) {                                    \
      fprintf(stderr, "error: %s\n", MXCapiGetLastError()); \
      return 1;                                           \
    }                                                     \
  } while (0)

int main(void) {
  CHECK(MXCapiInit());

  /* a = 2x3 ramp */
  int64_t shape[2] = {2, 3};
  void* a = NULL;
  CHECK(MXNDArrayCreate(shape, 2, "float32", &a));
  float host[6] = {0, 1, 2, 3, 4, 5};
  CHECK(MXNDArraySyncCopyFromCPU(a, host, sizeof(host)));

  /* b = transpose(a, axes=(1, 0)) — attrs as reference-style strings */
  const char* keys[] = {"axes"};
  const char* vals[] = {"(1, 0)"};
  void* outs[1];
  int nout = 0;
  void* ins[] = {a};
  CHECK(MXImperativeInvoke("transpose", ins, 1, keys, vals, 1, outs,
                           &nout, 1));

  int ndim = 0;
  int64_t oshape[8];
  CHECK(MXNDArrayGetShape(outs[0], &ndim, oshape, 8));
  float back[6];
  CHECK(MXNDArraySyncCopyToCPU(outs[0], back, sizeof(back)));

  printf("transpose -> (%lld, %lld): [%g %g %g %g %g %g]\n",
         (long long)oshape[0], (long long)oshape[1], back[0], back[1],
         back[2], back[3], back[4], back[5]);

  CHECK(MXNDArrayFree(outs[0]));
  CHECK(MXNDArrayFree(a));
  return 0;
}
