"""Per-model serving metrics.

Live counters ride the existing `profiler.Counter` API (so a running
profiler sees them as chrome-trace counter lanes under the "serving"
domain); the snapshot side is a plain dict / JSON string in the spirit
of `profiler.dumps()` — QPS, p50/p99 latency, batch occupancy, queue
depth, rejections, executor-cache hits.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, Optional

from .. import profiler

# completed-request latencies kept for percentile estimates; a bounded
# ring so a long-lived server's memory stays flat
_LATENCY_RING = 4096


def _percentile(sorted_vals, q: float) -> Optional[float]:
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


class ModelMetrics:
    """One model-version's serving counters + latency ring."""

    COUNTERS = (
        "requests", "completed", "failed", "rejected",
        "deadline_expired", "batches", "batched_rows", "padded_rows",
        "cache_hits", "cache_misses", "queue_depth",
    )

    def __init__(self, model: str, version: int):
        self.model, self.version = model, version
        prefix = f"serving/{model}/v{version}"
        self._c: Dict[str, profiler.Counter] = {
            name: profiler.Counter(f"{prefix}/{name}", domain="serving")
            for name in self.COUNTERS}
        self._lock = threading.Lock()
        self._lat = deque(maxlen=_LATENCY_RING)  # (done_t, latency_s)
        self._started = time.perf_counter()

    def bump(self, name: str, d: int = 1) -> None:
        self._c[name].increment(d)

    def gauge(self, name: str, v: int) -> None:
        self._c[name].set_value(v)

    def value(self, name: str) -> int:
        return self._c[name].value

    def observe_latency(self, seconds: float) -> None:
        with self._lock:
            self._lat.append((time.perf_counter(), seconds))

    def snapshot(self) -> dict:
        with self._lock:
            lat = list(self._lat)
        now = time.perf_counter()
        vals = sorted(s for _, s in lat)
        # QPS over the ring's span (a full ring measures the recent
        # window; a part-full ring measures since startup)
        span = (now - (lat[0][0] if len(lat) == self._lat.maxlen
                       else self._started)) or 1e-9
        batched = self.value("batched_rows")
        padded = self.value("padded_rows")
        snap = {name: self.value(name) for name in self.COUNTERS}
        snap.update({
            "model": self.model,
            "version": self.version,
            "qps": round(len(lat) / span, 3),
            "p50_latency_ms": None if not vals else
            round(_percentile(vals, 0.50) * 1e3, 3),
            "p99_latency_ms": None if not vals else
            round(_percentile(vals, 0.99) * 1e3, 3),
            # fraction of launched rows that were real requests (the
            # rest was bucket padding); 1.0 = no padding waste
            "batch_occupancy": None if not padded else
            round(batched / padded, 4),
            "mean_batch_rows": None if not snap["batches"] else
            round(batched / snap["batches"], 2),
        })
        return snap
