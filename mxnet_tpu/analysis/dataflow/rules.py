"""The mxflow rule set (MX008–MX012): whole-program rules over the
call graph + per-function CFG.

Each rule is grounded in a bug class this repo shipped and fixed in
PRs 6–7 (see docs/static_analysis.md for the catalogue):

  * MX008 — blocking call reachable while a first-party lock is held
    (the static complement of mxsan's dynamic lock-order detector,
    for paths tests never execute);
  * MX009 — transitive host sync in the Trainer/Updater/KVStore step
    chain (MX002 made fully interprocedural);
  * MX010 — resource acquired without a release on every exit path,
    exception paths included (the ``abandon_probe``/use-count class);
  * MX011 — caller-visible state mutated before the success point of a
    ``RetryPolicy``-wrapped callable (a retry would replay the
    mutation);
  * MX012 — buffer donation flowing across helper functions (MX005
    interprocedural): a caller's variable donated *inside* a callee.

All five follow the house precision-over-recall policy: an
unresolvable call contributes nothing, and every finding names the
evidence (the call path to the blocking/syncing/donating site).
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ..engine import FileContext, Rule, Violation, register_rule
# NOTE `from .cfg import ...`, never `from . import cfg`: the latter
# routes through a full dotted __import__ from the ROOT package and
# breaks the CLI's standalone (jax-free) load — see analysis/__init__.
from .cfg import CFG as _CFG, Block as _Block, build_cfg, can_raise
from .project import FuncInfo, Project, get_project
from .summaries import _FnExtractor, _attr_text, _call_ref

__all__ = ["BlockingUnderLock", "TransitiveHostSync",
           "ExceptionPathLeak", "RetryUnsafeSideEffect",
           "InterproceduralDonation"]


class _Anchor:
    """Minimal lineno/col carrier for ctx.violation()."""

    __slots__ = ("lineno", "col_offset")

    def __init__(self, lineno: int, col: int = 0):
        self.lineno = lineno
        self.col_offset = col


def _ref_text(ref: Optional[List[str]]) -> str:
    if not ref:
        return "<call>"
    kind = ref[0]
    if kind == "n":
        return f"{ref[1]}()"
    if kind == "self":
        return f"self.{ref[1]}()"
    if kind == "sattr":
        return f"self.{ref[1]}.{ref[2]}()"
    if kind in ("a", "lv"):
        return f"{ref[1]}.{ref[2]}()"
    if kind == "c":
        return f"{ref[1]}()"
    return "<call>"


class _ProjectRule(Rule):
    """Base for the interprocedural rules: record every FileContext,
    build (or share) the project in finalize()."""

    def __init__(self) -> None:
        self._ctxs: List[FileContext] = []

    def check(self, ctx: FileContext) -> Iterable[Violation]:
        self._ctxs.append(ctx)
        return ()

    def finalize(self) -> Iterable[Violation]:
        if not self._ctxs:
            return ()
        proj = get_project(self._ctxs)
        out: List[Violation] = []
        for ctx in self._ctxs:
            mod = proj.path_mod.get(ctx.path)
            if mod is None:
                continue
            for v in self._module_findings(proj, ctx, mod):
                if not ctx.suppressed(self.id, v.line):
                    out.append(v)
        return out

    def _module_findings(self, proj: Project, ctx: FileContext,
                         mod: str) -> Iterable[Violation]:
        raise NotImplementedError


# ---------------------------------------------------------------------------
# MX008 — blocking call while a first-party lock is held
# ---------------------------------------------------------------------------

@register_rule
class BlockingUnderLock(_ProjectRule):
    """MX008: a blocking operation (XLA compile, executor launch,
    collective, artifact/file IO, sleep/join/result/wait) executes —
    directly or through any chain of first-party calls — inside a
    ``with <lock>:`` region.  Every thread contending for that lock
    stalls behind a multi-millisecond (or multi-second) operation: the
    exact shape of the serving import stall and the compile-under-lock
    classes mxsan can only catch on paths tests actually run."""

    id = "MX008"
    name = "blocking-under-lock"
    description = ("Blocking call (compile/execute/collective/IO/"
                   "sleep/join) reachable while holding a first-party "
                   "lock — directly or through the call graph.")

    def _module_findings(self, proj: Project, ctx: FileContext,
                         mod: str) -> Iterable[Violation]:
        for fn in proj.funcs_of_module(mod):
            for entry, callees in fn.edges:
                lock = entry.get("lock")
                if not lock:
                    continue
                anchor = _Anchor(entry["line"])
                direct = entry.get("block")
                if direct:
                    yield ctx.violation(
                        self.id, anchor,
                        f"{direct} inside `with {lock}:` — every "
                        "thread contending for this lock stalls "
                        "behind it; hoist the blocking work out of "
                        "the lock (double-checked pattern, "
                        "ops/registry.py::jitted).")
                    continue
                for g in callees:
                    if g.t_blocks is None:
                        continue
                    path, _ = proj.witness_path(g.t_blocks, "blocks")
                    yield ctx.violation(
                        self.id, anchor,
                        f"{_ref_text(entry.get('ref'))} inside `with "
                        f"{lock}:` reaches a blocking operation "
                        f"({path or 'blocking call'}) — blocking "
                        "under a first-party lock serializes every "
                        "contending thread; move the call outside "
                        "the lock and publish the result under it.")
                    break


# ---------------------------------------------------------------------------
# MX009 — transitive host sync in the hot path
# ---------------------------------------------------------------------------

@register_rule
class TransitiveHostSync(_ProjectRule):
    """MX009: a call made from the Trainer/Updater/KVStore step chain
    (or inside an ``autograd.record()`` block) whose callee — any
    number of first-party calls deep, across modules, through methods
    and op-registry indirection — performs a device->host sync.  MX002
    flags the sync written *directly* in the hot scope; this rule
    follows the call graph, so wrapping ``.asnumpy()`` in two layers
    of logging helpers no longer hides the stall."""

    id = "MX009"
    name = "transitive-host-sync"
    description = ("Call from a Trainer/Updater/KVStore step-chain "
                   "method or record() block that transitively "
                   "reaches a device->host sync "
                   "(.asnumpy()/.item()/np.asarray).")

    def _module_findings(self, proj: Project, ctx: FileContext,
                         mod: str) -> Iterable[Violation]:
        for fn in proj.funcs_of_module(mod):
            hot_fn = fn.hot
            for entry, callees in fn.edges:
                if not (hot_fn or entry.get("record")):
                    continue
                if entry.get("sync"):
                    continue  # direct sync in the hot scope = MX002
                where = "in the step chain" if hot_fn \
                    else "inside autograd.record()"
                for g in callees:
                    if g.t_syncs is None or g.hot:
                        continue  # hot callees are flagged themselves
                    path, _ = proj.witness_path(g.t_syncs, "syncs")
                    yield ctx.violation(
                        self.id, _Anchor(entry["line"]),
                        f"call {where} reaches a device->host sync: "
                        f"{_ref_text(entry.get('ref'))} -> {path} — "
                        "the transfer stalls the async dispatch "
                        "pipeline; hoist the sync out of the hot "
                        "path or make the helper async.")
                    break


# ---------------------------------------------------------------------------
# MX010 — exception-path resource leak
# ---------------------------------------------------------------------------

#: acquire method -> matching release methods.  Only pairs with an
#: unambiguous protocol; the breaker probe (allow/abandon_probe) spans
#: threads and functions and is out of static scope.
_PAIRS = {"begin_use": ("end_use",),
          "acquire": ("release",)}


@register_rule
class ExceptionPathLeak(Rule):
    """MX010: a use-count / semaphore / lock acquired via
    ``X.begin_use()`` or ``X.acquire()`` with a matching release in
    the same function, where some path from the acquire to a function
    exit — **including the exception path** — misses the release.
    The release must dominate every exit: put it in a ``finally`` (or
    use a ``with``).  This is the PR 6/7 ``abandon_probe``/use-count
    leak class: one exception between acquire and release wedges the
    entry (or breaker, or pool slot) forever.

    A release that lives inside a nested function counts at the point
    that function is called *or escapes* (passed as a callback —
    ``Future.add_done_callback`` style deferred release)."""

    id = "MX010"
    cacheable = "file"
    name = "exception-path-leak"
    description = ("Resource acquire (begin_use/acquire) without a "
                   "release on every exit path incl. exceptions — "
                   "needs try/finally or with.")

    def check(self, ctx: FileContext) -> Iterable[Violation]:
        # cheap text pregate: most files contain no acquire verbs at
        # all, and building CFGs for them is pure waste
        src = "\n".join(ctx.lines)
        if not any(f".{name}(" in src for name in _PAIRS):
            return
        for fn in ctx.functions:
            yield from self._check_fn(ctx, fn)

    # ---- per-function analysis ---------------------------------------

    def _check_fn(self, ctx: FileContext,
                  fn: ast.AST) -> Iterable[Violation]:
        with_exprs: Set[int] = set()
        acquires: List[Tuple[ast.Call, str, str]] = []
        releases: Dict[Tuple[str, str], List[ast.AST]] = {}
        carriers: Set[str] = set()  # local defs performing a release
        nested: Dict[str, ast.AST] = {}
        for node in _same_scope_stmts(fn):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    with_exprs.add(id(item.context_expr))
        for node in _walk_scope(fn):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                nested[node.name] = node
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Call) and \
                            isinstance(sub.func, ast.Attribute) and \
                            any(sub.func.attr in rel
                                for rel in _PAIRS.values()):
                        carriers.add(node.name)
                        break
                continue
            if not isinstance(node, ast.Call) or \
                    not isinstance(node.func, ast.Attribute):
                continue
            meth = node.func.attr
            recv = _attr_text(node.func.value)
            if meth in _PAIRS and id(node) not in with_exprs:
                acquires.append((node, recv, meth))
            for acq, rels in _PAIRS.items():
                if meth in rels:
                    releases.setdefault((recv, acq), []).append(node)
        if not acquires:
            return
        # transitive carriers: a local def that calls a releasing def
        # releases too (`_done` -> `_release` -> entry.end_use())
        changed = True
        while changed:
            changed = False
            for name, node in nested.items():
                if name in carriers:
                    continue
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Call) and \
                            isinstance(sub.func, ast.Name) and \
                            sub.func.id in carriers:
                        carriers.add(name)
                        changed = True
                        break
        graph = build_cfg(fn)
        for call, recv, meth in acquires:
            key = (recv, meth)
            if key not in releases and not carriers:
                continue  # no local release: cross-function protocol
            if self._leaks(graph, fn, call, recv, meth, carriers):
                yield ctx.violation(
                    self.id, call,
                    f"`{recv}.{meth}()` has a path to a function exit "
                    "(including the exception path) with no matching "
                    f"`{recv}.{_PAIRS[meth][0]}()` — one exception "
                    "between acquire and release leaks the resource "
                    "forever. Release in a `finally:` (or use a "
                    "`with` block).")

    def _leaks(self, graph: "_CFG", fn: ast.AST, call: ast.Call,
               recv: str, meth: str, carriers: Set[str]) -> bool:
        rels = _PAIRS[meth]

        def releases_here(stmt: ast.stmt) -> bool:
            for n in _shallow_walk(stmt):
                if isinstance(n, ast.Call):
                    f = n.func
                    if isinstance(f, ast.Attribute) and f.attr in rels \
                            and _attr_text(f.value) == recv:
                        return True
                    # calling, or passing as a callback, a local def
                    # that performs the release
                    names = [a.id for a in n.args
                             if isinstance(a, ast.Name)]
                    if isinstance(f, ast.Name) and f.id in carriers:
                        return True
                    if any(nm in carriers for nm in names):
                        return True
            return False

        start = None
        for b in graph.blocks:
            if b.stmt is not None and any(
                    n is call for n in _shallow_walk(b.stmt)):
                start = b
                break
        if start is None:
            return False
        seen: Set[int] = set()
        # the acquire's OWN exception edge is not a leak path: if the
        # acquire call itself raises, nothing was acquired
        stack = [s for s in start.succs
                 if s not in (graph.exit_id, graph.raise_id)]
        while stack:
            bid = stack.pop()
            if bid in seen:
                continue
            seen.add(bid)
            b = graph.blocks[bid]
            if b.id in (graph.exit_id, graph.raise_id):
                return True  # reached an exit still holding
            if b.stmt is not None and releases_here(b.stmt):
                continue  # this path released; stop tracing it
            stack.extend(b.succs)
        return False


# ---------------------------------------------------------------------------
# MX011 — retry-unsafe side effects
# ---------------------------------------------------------------------------

_MUTATORS = {"append", "extend", "insert", "add", "update", "setdefault",
             "pop", "popitem", "clear", "remove", "discard"}


@register_rule
class RetryUnsafeSideEffect(Rule):
    """MX011: the callable handed to ``RetryPolicy.call`` mutates
    caller-visible state (``self.*``, closure/global names, containers
    that outlive the attempt) *before* an operation that can still
    fail.  A transient failure then replays the mutation: counters
    double-bump, partial writes land twice, published values go stale.
    The kvstore rule from PR 6: re-extract reads per attempt, write
    results only after the last fallible operation."""

    id = "MX011"
    cacheable = "file"
    name = "retry-unsafe-side-effect"
    description = ("RetryPolicy-wrapped callable mutates caller-"
                   "visible state before its success point — a "
                   "transient retry replays the mutation.")

    def check(self, ctx: FileContext) -> Iterable[Violation]:
        # pregate: no `.call(` in the file -> no RetryPolicy call sites
        src = "\n".join(ctx.lines)
        if ".call(" not in src:
            return
        module_fns = {n.name: n for n in ctx.tree.body
                      if isinstance(n, (ast.FunctionDef,
                                        ast.AsyncFunctionDef))}
        for fn in ctx.functions:
            local_fns = dict(module_fns)
            for node in _walk_scope(fn):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    local_fns[node.name] = node
            for node in _walk_scope(fn):
                if isinstance(node, ast.Call) and \
                        self._is_retry_call(node):
                    target = None
                    if node.args and isinstance(node.args[0], ast.Name):
                        target = local_fns.get(node.args[0].id)
                    if target is not None:
                        yield from self._check_attempt(ctx, target)

    @staticmethod
    def _is_retry_call(call: ast.Call) -> bool:
        f = call.func
        if not (isinstance(f, ast.Attribute) and f.attr == "call"):
            return False
        recv = f.value
        recv_text = _attr_text(recv).lower()
        if "policy" in recv_text or "retry" in recv_text:
            return True
        if isinstance(recv, ast.Call):
            inner = _attr_text(recv.func).lower()
            if "policy" in inner or "retry" in inner:
                return True
        # `.call(fn, site=...)` is the framework signature
        return any(k.arg == "site" for k in call.keywords)

    def _check_attempt(self, ctx: FileContext,
                       fn: ast.AST) -> Iterable[Violation]:
        if getattr(self, "_seen_attempts", None) is None:
            self._seen_attempts: Set[int] = set()
        if id(fn) in self._seen_attempts:
            return
        self._seen_attempts.add(id(fn))
        local_names = {a.arg for a in fn.args.args}
        declared: Set[str] = set()
        for n in _walk_scope(fn):
            if isinstance(n, (ast.Global, ast.Nonlocal)):
                declared.update(n.names)
            elif isinstance(n, ast.Name) and \
                    isinstance(n.ctx, ast.Store):
                local_names.add(n.id)
        local_names -= declared
        graph = build_cfg(fn)
        for b in graph.stmt_blocks():
            mut = self._mutation_in(b.stmt, local_names, declared)
            if mut is None:
                continue
            if self._risky_after(graph, b):
                node, what = mut
                yield ctx.violation(
                    self.id, node,
                    f"`{what}` mutates caller-visible state before "
                    "the retry success point — a transient failure "
                    "after this line replays the mutation on the "
                    "next attempt. Compute first, publish (write) "
                    "only after the last fallible operation.")

    def _mutation_in(self, stmt: ast.stmt, local_names: Set[str],
                     declared: Set[str]):
        # the statement node itself matters too: a bare Assign /
        # AugAssign IS the mutation (shallow-walk yields only children)
        for n in (stmt, *_shallow_walk(stmt)):
            if isinstance(n, (ast.Assign, ast.AugAssign)):
                targets = n.targets if isinstance(n, ast.Assign) \
                    else [n.target]
                for t in targets:
                    root = _root_name(t)
                    if isinstance(t, ast.Name):
                        if t.id in declared:
                            return n, f"{t.id} ="
                    elif root is not None and root not in local_names:
                        return n, f"{_attr_text(t) or root}[...] =" \
                            if isinstance(t, ast.Subscript) \
                            else f"{_attr_text(t)} ="
            elif isinstance(n, ast.Call) and \
                    isinstance(n.func, ast.Attribute) and \
                    n.func.attr in _MUTATORS:
                root = _root_name(n.func.value)
                if root is not None and root not in local_names:
                    return n, f"{_attr_text(n.func.value)}." \
                              f"{n.func.attr}(...)"
        return None

    @staticmethod
    def _risky_after(graph: "_CFG", block: "_Block") -> bool:
        seen: Set[int] = set()
        stack = list(block.succs)
        while stack:
            bid = stack.pop()
            if bid in seen:
                continue
            seen.add(bid)
            b = graph.blocks[bid]
            if b.stmt is not None and can_raise(b.stmt):
                return True
            stack.extend(b.succs)
        return False


# ---------------------------------------------------------------------------
# MX012 — donation flow across helpers
# ---------------------------------------------------------------------------

@register_rule
class InterproceduralDonation(_ProjectRule):
    """MX012: a variable is passed to a first-party helper that —
    directly or deeper in the call graph — donates that parameter to a
    compiled call (``donate_argnums``), and is then read again.  XLA
    invalidated the buffer inside the helper; the read returns garbage
    on TPU and "works" on CPU.  MX005 catches the donation written in
    the same scope; this rule follows it across functions, methods,
    and modules."""

    id = "MX012"
    name = "interprocedural-donation"
    description = ("Variable read after being passed to a helper "
                   "whose call chain donates that parameter "
                   "(donate_argnums) to a compiled function.")

    def _module_findings(self, proj: Project, ctx: FileContext,
                         mod: str) -> Iterable[Violation]:
        # donor gate: without at least one donating function in the
        # whole project (and its name in this file's text) there is
        # nothing a per-function scan could ever find
        if getattr(self, "_donor_proj", None) is not proj:
            self._donors = {f.name for f in proj.funcs.values()
                            if f.t_donates}
            self._donor_proj = proj
        donors = self._donors
        if not donors:
            return
        src = "\n".join(ctx.lines)
        if not any(f"{name}(" in src for name in donors):
            return
        for fn_node in ctx.functions:
            qual = self._qual_for(proj, ctx, mod, fn_node)
            fn = proj.funcs.get(qual) if qual else None
            if fn is None:
                continue
            yield from self._scan(proj, ctx, fn, fn_node)

    def _qual_for(self, proj: Project, ctx: FileContext, mod: str,
                  fn_node: ast.AST) -> Optional[str]:
        # top-level functions and methods only — nested defs have
        # "<locals>" quals and are scanned as part of their parent's
        # project record, not re-scanned here
        sym = ctx.symbol_at(fn_node.lineno)
        return None if sym == "<module>" else f"{mod}:{sym}"

    def _scan(self, proj: Project, ctx: FileContext, fn: FuncInfo,
              fn_node: ast.AST) -> Iterable[Violation]:
        ext = _FnExtractor.__new__(_FnExtractor)
        ext.rec = {"params": [], "blocks": None, "syncs": None,
                   "raises": False, "donates": {}, "calls": [],
                   "nested": {}}
        ext.local_types = {}
        ext.donating_vars = {}
        ext._prescan(fn_node)
        donated_at: Dict[str, Tuple[int, int, str]] = {}
        body = list(fn_node.body)
        for idx, stmt in enumerate(body):
            # 1) reads of names donated in an earlier statement
            for node in _shallow_walk_stmt_scope(stmt):
                if isinstance(node, ast.Name) and \
                        isinstance(node.ctx, ast.Load) and \
                        node.id in donated_at and \
                        donated_at[node.id][0] < idx:
                    _, line, path = donated_at.pop(node.id)
                    yield ctx.violation(
                        self.id, node,
                        f"`{node.id}` was donated inside the call on "
                        f"line {line} ({path}); its buffer is "
                        "invalidated — reading it here returns "
                        "garbage on TPU. Use the helper's result "
                        "instead.")
            # 2) helper calls that donate one of their params
            for node in _shallow_walk_stmt_scope(stmt):
                if not isinstance(node, ast.Call):
                    continue
                ref = _call_ref(node, ext.local_types)
                for g in proj.resolve_call(fn, {"ref": ref}):
                    if not g.t_donates:
                        continue
                    for pos in g.t_donates:
                        if pos < len(node.args) and \
                                isinstance(node.args[pos], ast.Name):
                            name = node.args[pos].id
                            path = self._donation_path(proj, g, pos)
                            donated_at.setdefault(
                                name, (idx, node.lineno, path))
            # 3) stores end the donated lifetime (incl. same-statement
            #    rebinds: `w = helper(w, g)` is the canonical idiom)
            for node in _shallow_walk_stmt_scope(stmt):
                if isinstance(node, ast.Name) and \
                        isinstance(node.ctx, ast.Store) and \
                        node.id in donated_at:
                    del donated_at[node.id]

    def _donation_path(self, proj: Project, g: FuncInfo,
                       pos: int) -> str:
        hops = [f"{g.name}() donates arg #{pos}"]
        fact = g.t_donates.get(pos)
        depth = 0
        while fact and fact[0] == "call" and depth < 5:
            callee = proj.funcs.get(fact[1])
            if callee is None:
                break
            hops.append(f"-> {callee.name}() arg #{fact[3]}")
            fact = callee.t_donates.get(fact[3])
            depth += 1
        if fact and fact[0] == "direct":
            hops.append(f"-> donate_argnums at line {fact[1]}")
        return " ".join(hops)


# ---------------------------------------------------------------------------
# scope-walk helpers (match the engine's conventions)
# ---------------------------------------------------------------------------

def _root_name(node: ast.AST) -> Optional[str]:
    """Root Name id of an Attribute/Subscript chain (``self._out[k]``
    -> "self"); None when the chain doesn't bottom out at a Name."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None

def _walk_scope(fn: ast.AST) -> Iterable[ast.AST]:
    """All nodes in the function's own scope; nested defs are yielded
    (so callers can index them) but not descended into."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda, ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(n))


def _same_scope_stmts(fn: ast.AST) -> Iterable[ast.AST]:
    for n in _walk_scope(fn):
        if isinstance(n, ast.stmt):
            yield n


def _shallow_walk(stmt: ast.stmt) -> Iterable[ast.AST]:
    """Nodes belonging to THIS statement only: for compound statements
    just the header expressions, never the nested statement bodies or
    nested function scopes."""
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef)):
        return
    headers: List[ast.AST] = []
    if isinstance(stmt, (ast.If, ast.While)):
        headers = [stmt.test]
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        headers = [stmt.target, stmt.iter]
    elif isinstance(stmt, (ast.With, ast.AsyncWith)):
        headers = [i.context_expr for i in stmt.items]
    elif isinstance(stmt, ast.Try):
        headers = []
    else:
        headers = list(ast.iter_child_nodes(stmt))
    stack = headers
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda, ast.ClassDef)):
            yield n
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


def _shallow_walk_stmt_scope(stmt: ast.stmt) -> Iterable[ast.AST]:
    """Every node under ``stmt`` except nested function/class scopes —
    the MX005-style statement-index scan granularity."""
    stack = [stmt]
    while stack:
        n = stack.pop()
        if n is not stmt and isinstance(
                n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                    ast.ClassDef)):
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))
