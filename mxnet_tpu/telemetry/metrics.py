"""Process-wide metrics registry: labeled Counter/Gauge/Histogram.

The serving and training layers record into ONE registry so a single
scrape (`/metrics`, Prometheus text exposition) or snapshot (JSON) sees
the whole process: request latencies, AOT-compile counts, training step
phases, collective times, data-wait.  Design constraints:

  * bounded memory — histograms use a FIXED exponential bucket ladder
    (no per-observation storage), so a long-lived server's footprint is
    flat no matter how much traffic it sees; percentile estimates come
    from bucket interpolation with error bounded by the ladder's ratio;
  * cheap hot path — a counter increment is one lock + one float add;
    label lookup is a dict hit on a tuple key, and instrument sites are
    expected to cache the child object (`family.labels(...)` once, then
    `child.inc()` per event);
  * standard exposition — `to_prometheus()` renders the text format
    (`# HELP` / `# TYPE` headers, one line per sample) that any
    Prometheus-compatible scraper ingests; `snapshot()` renders the
    same data as a JSON-able dict for the existing snapshot surfaces.
"""
from __future__ import annotations

import math
import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricFamily", "MetricsRegistry",
    "get_registry", "DEFAULT_LATENCY_BUCKETS", "exponential_buckets",
]


def exponential_buckets(start: float, factor: float, count: int) -> List[float]:
    """Fixed exponential ladder: ``start * factor**i`` for i in [0, count)."""
    if start <= 0 or factor <= 1 or count < 1:
        raise ValueError(
            f"exponential_buckets(start={start}, factor={factor}, "
            f"count={count}): need start > 0, factor > 1, count >= 1")
    return [start * factor ** i for i in range(count)]


# 100us .. ~105s in x2 steps: 21 buckets covers op dispatch through
# multi-second AOT compiles with <=2x relative quantile error per bucket.
DEFAULT_LATENCY_BUCKETS = exponential_buckets(1e-4, 2.0, 21)

_VALID_FIRST = set("abcdefghijklmnopqrstuvwxyz"
                   "ABCDEFGHIJKLMNOPQRSTUVWXYZ_:")
_VALID_REST = _VALID_FIRST | set("0123456789")


def _check_name(name: str) -> str:
    if not name or name[0] not in _VALID_FIRST \
            or any(c not in _VALID_REST for c in name):
        raise ValueError(
            f"metric name {name!r} is not a valid Prometheus name "
            f"([a-zA-Z_:][a-zA-Z0-9_:]*)")
    return name


def _escape_label(v: str) -> str:
    return (str(v).replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _fmt_value(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    if isinstance(v, float) and v.is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


def _labels_text(labels: "OrderedDict[str, str]",
                 extra: Optional[Tuple[str, str]] = None) -> str:
    items = list(labels.items())
    if extra is not None:
        items.append(extra)
    if not items:
        return ""
    return "{" + ",".join(f'{k}="{_escape_label(v)}"'
                          for k, v in items) + "}"


class Counter:
    """Monotone cumulative count.  One instance per label set."""

    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> float:
        if amount < 0:
            raise ValueError(f"counter increment must be >= 0, "
                             f"got {amount}")
        with self._lock:
            self._value += amount
            return self._value

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def reset(self) -> None:
        """Zero the counter — for lifecycle restarts (a fresh model
        entry re-registering its labels), not for steady-state use."""
        with self._lock:
            self._value = 0.0


class Gauge:
    """Point-in-time value (queue depth, occupancy, last wait)."""

    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def reset(self) -> None:
        self.set(0.0)


class Histogram:
    """Fixed-bucket histogram: cumulative counts per upper bound plus
    sum/count — exactly the Prometheus histogram data model, so both
    the text exposition and quantile estimation read straight off it.
    """

    __slots__ = ("_lock", "buckets", "_counts", "_sum", "_count")

    def __init__(self, buckets: Sequence[float]):
        bs = sorted(float(b) for b in buckets)
        if not bs:
            raise ValueError("histogram needs at least one bucket")
        self.buckets = bs  # upper bounds, +Inf implied
        self._lock = threading.Lock()
        self._counts = [0] * (len(bs) + 1)  # last slot = +Inf overflow
        self._sum = 0.0
        self._count = 0

    def observe(self, v: float) -> None:
        # linear scan: ladders are ~20 entries and the scan is
        # branch-predictable; bisect would pay more in call overhead
        i = 0
        bs = self.buckets
        n = len(bs)
        while i < n and v > bs[i]:
            i += 1
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def cumulative(self) -> List[Tuple[float, int]]:
        """[(upper_bound, cumulative_count)] ending with (+Inf, total)."""
        with self._lock:
            counts = list(self._counts)
        out, acc = [], 0
        for ub, c in zip(self.buckets + [math.inf], counts):
            acc += c
            out.append((ub, acc))
        return out

    def quantile(self, q: float) -> Optional[float]:
        """Estimate the q-quantile by linear interpolation inside the
        bucket where the cumulative count crosses q*total.  Error is
        bounded by the bucket width (the ladder's exponential factor).
        """
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile q must be in [0, 1], got {q}")
        cum = self.cumulative()
        total = cum[-1][1]
        if total == 0:
            return None
        rank = q * total
        lo = 0.0
        prev_c = 0
        for ub, c in cum:
            if c >= rank:
                if ub == math.inf:
                    return lo  # overflow bucket: best effort = last ub
                if c == prev_c:
                    return ub
                frac = (rank - prev_c) / (c - prev_c)
                return lo + frac * (ub - lo)
            lo, prev_c = ub, c
        return cum[-1][0]

    def reset(self) -> None:
        with self._lock:
            self._counts = [0] * (len(self.buckets) + 1)
            self._sum = 0.0
            self._count = 0


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class MetricFamily:
    """One named metric + its per-label-set children."""

    def __init__(self, name: str, kind: str, help: str = "",
                 labelnames: Sequence[str] = (),
                 buckets: Optional[Sequence[float]] = None):
        self.name = _check_name(name)
        if kind not in _KINDS:
            raise ValueError(f"unknown metric kind {kind!r}")
        self.kind = kind
        self.help = help
        self.labelnames = tuple(labelnames)
        for ln in self.labelnames:
            _check_name(ln)
        # stored sorted: children sort anyway, and idempotent
        # re-registration compares ladders order-insensitively
        self.buckets = sorted(float(b) for b in buckets) \
            if buckets is not None else list(DEFAULT_LATENCY_BUCKETS)
        self._lock = threading.Lock()
        self._children: "OrderedDict[tuple, object]" = OrderedDict()

    def _make_child(self):
        if self.kind == "histogram":
            return Histogram(self.buckets)
        return _KINDS[self.kind]()

    def labels(self, *values, **kv):
        """Get-or-create the child for one label set.  Accepts either
        positional values (in labelnames order) or keywords."""
        if values and kv:
            raise ValueError("pass labels positionally or by keyword, "
                             "not both")
        if kv:
            if set(kv) != set(self.labelnames):
                raise ValueError(
                    f"metric {self.name!r} has labels "
                    f"{self.labelnames}, got {sorted(kv)}")
            values = tuple(str(kv[n]) for n in self.labelnames)
        else:
            if len(values) != len(self.labelnames):
                raise ValueError(
                    f"metric {self.name!r} takes {len(self.labelnames)} "
                    f"label values, got {len(values)}")
            values = tuple(str(v) for v in values)
        with self._lock:
            child = self._children.get(values)
            if child is None:
                child = self._make_child()
                self._children[values] = child
        return child

    def reset_labels(self, *values, **kv):
        """Zero (creating if absent) one label set's child — the
        lifecycle-restart hook for a re-registered model entry."""
        child = self.labels(*values, **kv)
        child.reset()
        return child

    def children(self) -> List[Tuple[tuple, object]]:
        with self._lock:
            return list(self._children.items())

    # the no-label fast path: a family declared with labelnames=() acts
    # as a single metric
    def _solo(self):
        return self.labels()

    def inc(self, amount: float = 1.0):
        self._solo().inc(amount)

    def dec(self, amount: float = 1.0):
        self._solo().dec(amount)

    def set(self, v: float):
        self._solo().set(v)

    def observe(self, v: float):
        self._solo().observe(v)

    @property
    def value(self):
        return self._solo().value


class MetricsRegistry:
    """Name -> MetricFamily.  Registration is idempotent: asking for an
    existing (name, kind) returns the existing family (labelnames and
    bucket ladder must match); a kind clash raises."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families: "OrderedDict[str, MetricFamily]" = OrderedDict()
        # bumped by clear(); child caches (telemetry.instruments, op
        # dispatch) key their validity on it so a cleared registry
        # never keeps receiving samples into orphaned children
        self.generation = 0
        # name -> zero-arg callable run before every exposition, for
        # point-in-time process gauges (uptime, RSS, build info) that
        # must be fresh at scrape time rather than at some event time
        self._collectors: "OrderedDict[str, object]" = OrderedDict()

    def add_collector(self, name: str, fn) -> None:
        """Register (idempotently, by name) a pre-scrape refresher.  A
        collector must be cheap and must never raise into a scrape —
        failures are swallowed (the scrape serves stale/absent samples
        instead of a 500)."""
        with self._lock:
            self._collectors[name] = fn

    def _run_collectors(self) -> None:
        with self._lock:
            fns = list(self._collectors.values())
        for fn in fns:
            try:
                fn()
            except Exception:  # noqa: BLE001 — scrape must not 500
                pass

    def _get_or_make(self, name: str, kind: str, help: str,
                     labelnames: Sequence[str],
                     buckets: Optional[Sequence[float]] = None
                     ) -> MetricFamily:
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if fam.kind != kind:
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{fam.kind}, not {kind}")
                if tuple(labelnames) != fam.labelnames:
                    raise ValueError(
                        f"metric {name!r} already registered with "
                        f"labels {fam.labelnames}, not "
                        f"{tuple(labelnames)}")
                if kind == "histogram" and buckets is not None \
                        and sorted(float(b) for b in buckets) \
                        != fam.buckets:
                    raise ValueError(
                        f"metric {name!r} already registered with "
                        f"bucket ladder {fam.buckets}; observations "
                        f"on a different ladder would skew quantiles")
                if help and not fam.help:
                    fam.help = help
                return fam
            fam = MetricFamily(name, kind, help=help,
                               labelnames=labelnames, buckets=buckets)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help: str = "",
                labels: Sequence[str] = ()) -> MetricFamily:
        return self._get_or_make(name, "counter", help, labels)

    def gauge(self, name: str, help: str = "",
              labels: Sequence[str] = ()) -> MetricFamily:
        return self._get_or_make(name, "gauge", help, labels)

    def histogram(self, name: str, help: str = "",
                  labels: Sequence[str] = (),
                  buckets: Optional[Sequence[float]] = None
                  ) -> MetricFamily:
        return self._get_or_make(name, "histogram", help, labels,
                                 buckets=buckets)

    def get(self, name: str) -> Optional[MetricFamily]:
        with self._lock:
            return self._families.get(name)

    def families(self) -> List[MetricFamily]:
        with self._lock:
            return list(self._families.values())

    def clear(self) -> None:
        """Drop every family — test isolation only.  Bumps the
        generation so cached children elsewhere are re-resolved."""
        with self._lock:
            self._families.clear()
            self.generation += 1
            # collectors survive: they are registered once per process
            # (telemetry.instruments import) and re-create their
            # families on the next scrape of the cleared registry

    # ---- exposition ----------------------------------------------------

    def to_prometheus(self) -> str:
        """Prometheus text exposition format 0.0.4: `# HELP`/`# TYPE`
        headers, one line per sample, histogram `_bucket`/`_sum`/
        `_count` expansion."""
        self._run_collectors()
        out: List[str] = []
        for fam in self.families():
            out.append(f"# HELP {fam.name} "
                       f"{fam.help or fam.name}".rstrip())
            out.append(f"# TYPE {fam.name} {fam.kind}")
            for values, child in fam.children():
                labels = OrderedDict(zip(fam.labelnames, values))
                if fam.kind == "histogram":
                    for ub, cum in child.cumulative():
                        out.append(
                            f"{fam.name}_bucket"
                            f"{_labels_text(labels, ('le', _fmt_value(ub)))}"
                            f" {cum}")
                    out.append(f"{fam.name}_sum{_labels_text(labels)} "
                               f"{_fmt_value(child.sum)}")
                    out.append(f"{fam.name}_count{_labels_text(labels)} "
                               f"{child.count}")
                else:
                    out.append(f"{fam.name}{_labels_text(labels)} "
                               f"{_fmt_value(child.value)}")
        return "\n".join(out) + "\n"

    def snapshot(self) -> dict:
        """JSON-able mirror of the exposition (the `dumps()`-style
        surface the serving snapshot already speaks)."""
        self._run_collectors()
        snap: Dict[str, dict] = {}
        for fam in self.families():
            samples = []
            for values, child in fam.children():
                labels = dict(zip(fam.labelnames, values))
                if fam.kind == "histogram":
                    samples.append({
                        "labels": labels,
                        "count": child.count,
                        "sum": child.sum,
                        "p50": child.quantile(0.50),
                        "p95": child.quantile(0.95),
                        "p99": child.quantile(0.99),
                    })
                else:
                    samples.append({"labels": labels,
                                    "value": child.value})
            snap[fam.name] = {"type": fam.kind, "help": fam.help,
                              "samples": samples}
        return snap


_REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    """The process-wide default registry every instrument site uses."""
    return _REGISTRY
