"""BERT pretraining example (BASELINE config 3: BERT-base).

Synthetic-corpus MLM + NSP pretraining loop over the BERT stack: fused
attention (Pallas on TPU), tied MLM decoder, NSP classifier.  The
reference-era equivalent is GluonNLP's scripts/bert/run_pretraining.py.

Usage:
  python examples/bert_pretrain.py                  # TPU, bert-base
  python examples/bert_pretrain.py --cpu --small    # CPU smoke (CI)
  python examples/bert_pretrain.py --corpus wiki.txt --steps 10000
      # REAL-DATA path: any plain-text file(s), one document per line;
      # a whitespace vocab is built, sentence pairs sampled for NSP and
      # 15% of tokens masked for MLM (BERT paper recipe)
"""
from __future__ import annotations

import argparse
import time


class _CorpusSampler:
    """Real-data MLM+NSP batches from plain text (the BERT paper recipe
    over a whitespace vocabulary — the wordpiece step of GluonNLP's
    run_pretraining.py data pipeline is out of scope, everything else is
    the same: sentence-pair NSP sampling, 15% masking with 80/10/10)."""

    PAD, UNK, CLS, SEP, MASK = 0, 1, 2, 3, 4

    def __init__(self, paths, max_vocab, seq_len, rng):
        from collections import Counter

        self.seq_len = seq_len
        self.rng = rng
        docs = []
        counts = Counter()
        for p in paths:
            with open(p) as f:
                for line in f:
                    sents = [s.split() for s in line.strip().split(". ")
                             if s.split()]
                    if len(sents) >= 2:
                        docs.append(sents)
                        for s in sents:
                            counts.update(s)
        if not docs:
            raise SystemExit("corpus: need lines with >=2 sentences")
        vocab = [w for w, _ in counts.most_common(max_vocab - 5)]
        self.w2i = {w: i + 5 for i, w in enumerate(vocab)}
        self.vocab_size = len(self.w2i) + 5
        self.docs = docs

    def _ids(self, sent):
        return [self.w2i.get(w, self.UNK) for w in sent]

    def _pair(self):
        rng = self.rng
        d = self.docs[rng.randint(len(self.docs))]
        i = rng.randint(len(d) - 1)
        a = self._ids(d[i])
        if rng.rand() < 0.5 or len(self.docs) < 2:
            b, is_next = self._ids(d[i + 1]), 1
        else:
            # negative: a sentence from a DIFFERENT document (the BERT
            # recipe — sampling the same doc could yield a true
            # next-sentence pair mislabeled 0)
            while True:
                j = rng.randint(len(self.docs))
                if self.docs[j] is not d:
                    break
            rd = self.docs[j]
            b, is_next = self._ids(rd[rng.randint(len(rd))]), 0
        budget = self.seq_len - 3
        a = a[: budget // 2]
        b = b[: budget - len(a)]
        toks = [self.CLS] + a + [self.SEP] + b + [self.SEP]
        segs = [0] * (len(a) + 2) + [1] * (len(b) + 1)
        return toks, segs, is_next

    def batch(self, b, ctx):
        import numpy as np
        from mxnet_tpu import nd

        s = self.seq_len
        toks = np.zeros((b, s), np.int64)
        segs = np.zeros((b, s), np.int64)
        vlen = np.zeros((b,), np.float32)
        labels = np.zeros((b, s), np.int64)
        weight = np.zeros((b, s), np.float32)
        nsp = np.zeros((b,), np.float32)
        for k in range(b):
            t, g, is_next = self._pair()
            n = len(t)
            vlen[k] = n
            nsp[k] = is_next
            t = np.asarray(t + [self.PAD] * (s - n))
            segs[k, :n] = g
            labels[k] = t
            # mask 15% of real (non-special) positions: 80% [MASK],
            # 10% random, 10% kept
            cand = [i for i in range(n)
                    if t[i] not in (self.CLS, self.SEP, self.PAD)]
            self.rng.shuffle(cand)
            n_mask = max(1, int(0.15 * len(cand)))
            for i in cand[:n_mask]:
                weight[k, i] = 1.0
                r = self.rng.rand()
                if r < 0.8:
                    t[i] = self.MASK
                elif r < 0.9:
                    t[i] = self.rng.randint(5, self.vocab_size)
            toks[k] = t
        f = np.float32
        return (nd.array(toks.astype(f), ctx=ctx),
                nd.array(segs.astype(f), ctx=ctx),
                nd.array(vlen, ctx=ctx),
                nd.array(labels.astype(f), ctx=ctx),
                nd.array(weight, ctx=ctx),
                nd.array(nsp, ctx=ctx))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--small", action="store_true")
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--seq-len", type=int, default=128)
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--vocab", type=int, default=30522)
    ap.add_argument("--dtype", default="float32")
    ap.add_argument("--corpus", default=None,
                    help="comma-separated text files (one document per "
                         "line) for real-data MLM+NSP pretraining")
    args = ap.parse_args()

    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")

    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, nd
    from mxnet_tpu.gluon import Trainer
    from mxnet_tpu.gluon.loss import SoftmaxCrossEntropyLoss
    from mxnet_tpu.gluon.model_zoo.bert import get_bert_model

    ctx = mx.cpu() if args.cpu else mx.tpu(0)
    rng = np.random.RandomState(0)
    if args.small:
        args.vocab, args.seq_len, args.batch_size = 1000, 32, 4
    b, s = args.batch_size, args.seq_len

    # the sampler is built FIRST so the model's embedding + MLM decoder
    # are sized to the corpus's actual vocabulary
    sampler = None
    if args.corpus:
        sampler = _CorpusSampler(args.corpus.split(","), args.vocab, s,
                                 rng)
        args.vocab = sampler.vocab_size

    if args.small:
        net = get_bert_model("bert_12_768_12", vocab_size=args.vocab,
                             num_layers=2, units=64, hidden_size=128,
                             num_heads=4, max_length=args.seq_len)
    else:
        net = get_bert_model("bert_12_768_12", vocab_size=args.vocab,
                             max_length=max(512, args.seq_len))
    net.initialize(mx.initializer.Normal(0.02), ctx=ctx)
    if args.dtype != "float32":
        net.cast(args.dtype)

    loss_fn = SoftmaxCrossEntropyLoss()
    trainer = Trainer(net.collect_params(), "adam",
                      {"learning_rate": 1e-4})

    if sampler is not None:
        def next_batch():
            return sampler.batch(b, ctx)
    else:
        tokens = nd.array(
            rng.randint(0, args.vocab, (b, s)).astype("float32"), ctx=ctx)
        segments = nd.zeros((b, s), ctx=ctx)
        vlen = nd.array(np.full(b, s, "float32"), ctx=ctx)
        mlm_labels = nd.array(
            rng.randint(0, args.vocab, (b, s)).astype("float32"), ctx=ctx)
        mlm_weight = nd.array(np.ones((b, s), "float32"), ctx=ctx)
        nsp_labels = nd.array(rng.randint(0, 2, (b,)).astype("float32"),
                              ctx=ctx)

        def next_batch():
            return tokens, segments, vlen, mlm_labels, mlm_weight, \
                nsp_labels

    step_time = None
    for step in range(args.steps):
        tic = time.time()
        (tokens, segments, vlen, mlm_labels, mlm_weight,
         nsp_labels) = next_batch()
        with autograd.record():
            seq, pooled = net(tokens, segments, vlen)
            mlm_scores = net.decode_mlm(seq)
            nsp_scores = net.classify_nsp(pooled)
            # masked mean over the predicted positions: gluon losses
            # apply sample_weight per token, then mean over the seq axis
            per_sample = loss_fn(mlm_scores, mlm_labels,
                                 mlm_weight.expand_dims(-1))
            denom = nd.maximum(mlm_weight.sum(),
                               nd.ones((1,), ctx=ctx))
            mlm_l = per_sample.sum() * float(s) / denom
            loss = mlm_l + loss_fn(nsp_scores, nsp_labels).mean()
        loss.backward()
        trainer.step(b)
        lval = float(loss.asnumpy())  # sync point ends the step timing
        step_time = time.time() - tic
        print(f"step {step}: loss={lval:.4f} ({step_time:.2f}s)")
    if step_time is not None:
        print(f"last-step throughput: {b * s / step_time:.0f} tokens/s")


if __name__ == "__main__":
    main()
