"""Gluon losses (ref: python/mxnet/gluon/loss.py): L2Loss, L1Loss,
SigmoidBinaryCrossEntropyLoss, SoftmaxCrossEntropyLoss, KLDivLoss, CTCLoss,
HuberLoss, HingeLoss, SquaredHingeLoss, LogisticLoss, TripletLoss,
CosineEmbeddingLoss, PoissonNLLLoss."""
from __future__ import annotations

from .block import HybridBlock

__all__ = ["Loss", "L2Loss", "L1Loss", "SigmoidBinaryCrossEntropyLoss",
           "SigmoidBCELoss", "SoftmaxCrossEntropyLoss", "SoftmaxCELoss",
           "KLDivLoss", "CTCLoss", "HuberLoss", "HingeLoss",
           "SquaredHingeLoss", "LogisticLoss", "TripletLoss",
           "CosineEmbeddingLoss", "PoissonNLLLoss"]


def _apply_weighting(F, loss, weight=None, sample_weight=None):
    if sample_weight is not None:
        loss = F.broadcast_mul(loss, sample_weight)
    if weight is not None:
        loss = loss * weight
    return loss


def _reshape_like(F, x, y):
    return F.reshape_like(x, y) if hasattr(x, "reshape") else x.reshape(y.shape)


class Loss(HybridBlock):
    def __init__(self, weight, batch_axis, **kwargs):
        super().__init__(**kwargs)
        self._weight = weight
        self._batch_axis = batch_axis

    def _mean_nonbatch(self, F, loss):
        axes = tuple(i for i in range(getattr(loss, "ndim", len(loss.shape)))
                     if i != self._batch_axis)
        return F.mean(loss, axis=axes) if axes else loss


class L2Loss(Loss):
    def __init__(self, weight=1.0, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = F.reshape(label, shape=pred.shape)
        loss = F.square(label - pred)
        loss = _apply_weighting(F, loss, self._weight / 2, sample_weight)
        return self._mean_nonbatch(F, loss)


class L1Loss(Loss):
    def __init__(self, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = F.reshape(label, shape=pred.shape)
        loss = F.abs(label - pred)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return self._mean_nonbatch(F, loss)


class SigmoidBinaryCrossEntropyLoss(Loss):
    def __init__(self, from_sigmoid=False, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_sigmoid = from_sigmoid

    def hybrid_forward(self, F, pred, label, sample_weight=None,
                       pos_weight=None):
        label = F.reshape(label, shape=pred.shape)
        if not self._from_sigmoid:
            # log(1+exp(-|x|)) + max(x,0) - x*z  (numerically stable)
            if pos_weight is None:
                loss = F.relu(pred) - pred * label + \
                    F.Activation(-F.abs(pred), act_type="softrelu")
            else:
                log_weight = 1 + F.broadcast_mul(pos_weight - 1, label)
                loss = pred - pred * label + log_weight * (
                    F.Activation(-F.abs(pred), act_type="softrelu")
                    + F.relu(-pred))
        else:
            eps = 1e-12
            if pos_weight is None:
                loss = -(F.log(pred + eps) * label
                         + F.log(1.0 - pred + eps) * (1.0 - label))
            else:
                loss = -(F.broadcast_mul(F.log(pred + eps) * label, pos_weight)
                         + F.log(1.0 - pred + eps) * (1.0 - label))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return self._mean_nonbatch(F, loss)


SigmoidBCELoss = SigmoidBinaryCrossEntropyLoss


class SoftmaxCrossEntropyLoss(Loss):
    """ref: loss.py::SoftmaxCrossEntropyLoss (sparse_label, from_logits)."""

    def __init__(self, axis=-1, sparse_label=True, from_logits=False,
                 weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._axis = axis
        self._sparse_label = sparse_label
        self._from_logits = from_logits

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = F.log_softmax(pred, axis=self._axis)
        if self._sparse_label:
            loss = -F.pick(pred, label, axis=self._axis, keepdims=True)
        else:
            label = _reshape_like(F, label, pred)
            loss = -F.sum(pred * label, axis=self._axis, keepdims=True)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return self._mean_nonbatch(F, loss)


SoftmaxCELoss = SoftmaxCrossEntropyLoss


class KLDivLoss(Loss):
    def __init__(self, from_logits=True, axis=-1, weight=None, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._axis = axis

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = F.log_softmax(pred, axis=self._axis)
        loss = label * (F.log(label + 1e-12) - pred)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return self._mean_nonbatch(F, loss)


class CTCLoss(Loss):
    def __init__(self, layout="NTC", label_layout="NT", weight=None, **kwargs):
        super().__init__(weight, 0, **kwargs)
        self._layout = layout
        self._label_layout = label_layout

    def hybrid_forward(self, F, pred, label, pred_lengths=None,
                       label_lengths=None, sample_weight=None):
        if self._layout == "NTC":
            pred = F.swapaxes(pred, dim1=0, dim2=1)
        if self._label_layout == "TN":
            label = F.swapaxes(label, dim1=0, dim2=1)
        loss = F.CTCLoss(pred, label,
                         use_data_lengths=pred_lengths is not None,
                         use_label_lengths=label_lengths is not None,
                         **({"data_lengths": pred_lengths} if pred_lengths is not None else {}),
                         **({"label_lengths": label_lengths} if label_lengths is not None else {}),
                         blank_label="last")
        return _apply_weighting(F, loss, self._weight, sample_weight)


class HuberLoss(Loss):
    def __init__(self, rho=1.0, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._rho = rho

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = F.reshape(label, shape=pred.shape)
        loss = F.abs(label - pred)
        loss = F.where(loss > self._rho,
                       loss - 0.5 * self._rho,
                       (0.5 / self._rho) * F.square(loss))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return self._mean_nonbatch(F, loss)


class HingeLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = F.reshape(label, shape=pred.shape)
        loss = F.relu(self._margin - pred * label)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return self._mean_nonbatch(F, loss)


class SquaredHingeLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = F.reshape(label, shape=pred.shape)
        loss = F.square(F.relu(self._margin - pred * label))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return self._mean_nonbatch(F, loss)


class LogisticLoss(Loss):
    def __init__(self, weight=None, batch_axis=0, label_format="signed",
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._label_format = label_format

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = F.reshape(label, shape=pred.shape)
        if self._label_format == "signed":
            label = (label + 1.0) / 2.0
        loss = F.relu(pred) - pred * label + \
            F.Activation(-F.abs(pred), act_type="softrelu")
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return self._mean_nonbatch(F, loss)


class TripletLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, positive, negative, sample_weight=None):
        positive = _reshape_like(F, positive, pred)
        negative = _reshape_like(F, negative, pred)
        loss = F.sum(F.square(positive - pred) - F.square(negative - pred),
                     axis=self._batch_axis, exclude=True)
        loss = F.relu(loss + self._margin)
        return _apply_weighting(F, loss, self._weight, sample_weight)


class CosineEmbeddingLoss(Loss):
    def __init__(self, weight=None, batch_axis=0, margin=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, input1, input2, label, sample_weight=None):
        input1 = F.reshape(input1, shape=(input1.shape[0], -1))
        input2 = F.reshape(input2, shape=(input2.shape[0], -1))
        cos = F.sum(input1 * input2, axis=-1) / (
            F.norm(input1, axis=-1) * F.norm(input2, axis=-1) + 1e-12)
        label = F.reshape(label, shape=(-1,))
        loss = F.where(label == 1, 1.0 - cos, F.relu(cos - self._margin))
        return _apply_weighting(F, loss, self._weight, sample_weight)


class PoissonNLLLoss(Loss):
    def __init__(self, weight=None, from_logits=True, batch_axis=0,
                 compute_full=False, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._compute_full = compute_full

    def hybrid_forward(self, F, pred, target, sample_weight=None, epsilon=1e-08):
        target = _reshape_like(F, target, pred)
        if self._from_logits:
            loss = F.exp(pred) - target * pred
        else:
            loss = pred - target * F.log(pred + epsilon)
        if self._compute_full:
            stirling = target * F.log(target + epsilon) - target \
                + 0.5 * F.log(2 * 3.141592653589793 * (target + epsilon))
            stirling = F.where(target <= 1, F.zeros_like(target), stirling)
            loss = loss + stirling
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss)
