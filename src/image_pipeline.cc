// Native image data pipeline: .rec shards -> decoded/augmented batches.
//
// TPU-native counterpart of the reference's threaded image pipeline
// (ref: src/io/iter_image_recordio_2.cc ImageRecordIOParser2 +
// image_aug_default.cc DefaultImageAugmenter + dmlc ThreadedIter).
// Differences by design, not omission:
//   * decode/augment tasks are scheduled on the N1 dependency Engine
//     (engine.{h,cc}) instead of a bespoke OMP loop — one scheduler for
//     all host-side work;
//   * the default output is uint8 NHWC batches: normalization runs on
//     the TPU fused into the first conv (bf16), and uint8 host->device
//     transfer is 4x cheaper than float32 over the host link.  A
//     `normalize=1` mode emits float32 NCHW (mean/std applied) for
//     drop-in parity with the Python ImageRecordIter contract.
//
// Built as a SEPARATE shared object (libmxnet_tpu_image.so) because it
// links OpenCV (the reference links OpenCV for the same role); the core
// native library keeps zero image dependencies.
#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <random>
#include <string>
#include <thread>
#include <vector>

#include <opencv2/core.hpp>
#include <opencv2/imgcodecs.hpp>
#include <opencv2/imgproc.hpp>

#include "base.h"
#include "engine.h"

namespace mxt {

// ---- wire format helpers (matches recordio.cc / recordio.py) -------------

static const uint32_t kMagic = 0x3ed7230a;
static const int kCFlagBits = 29;
static const uint32_t kLenMask = (1u << kCFlagBits) - 1;

struct IRHeader {  // ref: python recordio.py IRHeader "<IfQQ"
  uint32_t flag;
  float label;
  uint64_t id;
  uint64_t id2;
};

// ---- config --------------------------------------------------------------

struct PipelineCfg {
  int batch = 1;
  int channels = 3;
  int height = 224;
  int width = 224;
  int label_width = 1;
  int resize_short = -1;   // resize shorter edge before crop; -1 = off
  bool rand_crop = false;  // random vs center crop
  bool rand_mirror = false;
  bool shuffle = false;    // random order via the .idx sidecar
  bool normalize = false;  // emit float32 NCHW (mean/std) instead of u8 NHWC
  float mean[3] = {0, 0, 0};
  float std[3] = {1, 1, 1};
  int threads = 4;
  int prefetch = 4;  // max in-flight batches
  uint64_t seed = 0;
};

// "key=value;key=value" — extensible without ABI churn (the ctypes
// counterpart of dmlc::Parameter kwargs init)
static PipelineCfg ParseCfg(const std::string& s) {
  PipelineCfg c;
  size_t pos = 0;
  while (pos < s.size()) {
    size_t eq = s.find('=', pos);
    if (eq == std::string::npos) break;
    size_t end = s.find(';', eq);
    if (end == std::string::npos) end = s.size();
    std::string k = s.substr(pos, eq - pos);
    std::string v = s.substr(eq + 1, end - eq - 1);
    double d = atof(v.c_str());
    if (k == "batch") c.batch = (int)d;
    else if (k == "channels") c.channels = (int)d;
    else if (k == "height") c.height = (int)d;
    else if (k == "width") c.width = (int)d;
    else if (k == "label_width") c.label_width = (int)d;
    else if (k == "resize_short") c.resize_short = (int)d;
    else if (k == "rand_crop") c.rand_crop = d != 0;
    else if (k == "rand_mirror") c.rand_mirror = d != 0;
    else if (k == "shuffle") c.shuffle = d != 0;
    else if (k == "normalize") c.normalize = d != 0;
    else if (k == "mean_r") c.mean[0] = (float)d;
    else if (k == "mean_g") c.mean[1] = (float)d;
    else if (k == "mean_b") c.mean[2] = (float)d;
    else if (k == "std_r") c.std[0] = (float)d;
    else if (k == "std_g") c.std[1] = (float)d;
    else if (k == "std_b") c.std[2] = (float)d;
    else if (k == "threads") c.threads = (int)d;
    else if (k == "prefetch") c.prefetch = (int)d;
    else if (k == "seed") c.seed = (uint64_t)d;
    pos = end + 1;
  }
  return c;
}

// ---- batches -------------------------------------------------------------

struct Batch {
  uint64_t seq;
  std::vector<uint8_t> data;   // u8 NHWC or f32 NCHW (bytes)
  std::vector<float> label;    // batch * label_width
  std::atomic<int> remaining{0};
  int pad = 0;
};

struct DecodeTask {
  class ImagePipeline* pipe;
  Batch* batch;
  int slot;
  std::string raw;  // full record (IRHeader + encoded image)
  uint64_t rng_seed;
};

// ---- the pipeline --------------------------------------------------------

class ImagePipeline {
 public:
  ImagePipeline(const std::string& rec_path, const std::string& idx_path,
                const std::string& cfg_str)
      : cfg_(ParseCfg(cfg_str)),
        rec_path_(rec_path),
        engine_(std::max(1, cfg_.threads)) {
    f_ = std::fopen(rec_path.c_str(), "rb");
    MXT_CHECK_MSG(f_ != nullptr, "cannot open " + rec_path);
    if (!idx_path.empty()) LoadIdx(idx_path);
    MXT_CHECK_MSG(!cfg_.shuffle || !offsets_.empty(),
                  "shuffle=1 requires a .idx sidecar");
    StartEpoch();
  }

  ~ImagePipeline() {
    {
      std::lock_guard<std::mutex> lk(m_);
      stop_ = true;
      cv_space_.notify_all();
      cv_out_.notify_all();
    }
    if (reader_.joinable()) reader_.join();
    engine_.WaitForAll();
    for (auto& kv : done_) delete kv.second;
    if (f_) std::fclose(f_);
  }

  // next completed batch in order; nullptr at epoch end
  Batch* Next() {
    std::unique_lock<std::mutex> lk(m_);
    cv_out_.wait(lk, [this] {
      return stop_ || !error_.empty() ||
             (!done_.empty() && done_.begin()->first == next_out_) ||
             (reader_eof_ && next_out_ == next_seq_);
    });
    if (stop_) return nullptr;
    if (!error_.empty()) throw NativeError(error_);
    auto it = done_.find(next_out_);
    if (it == done_.end()) return nullptr;  // epoch exhausted
    Batch* b = it->second;
    done_.erase(it);
    ++next_out_;
    in_flight_--;
    cv_space_.notify_one();
    return b;
  }

  void Reset() {
    {
      std::lock_guard<std::mutex> lk(m_);
      stop_ = true;
      cv_space_.notify_all();
    }
    if (reader_.joinable()) reader_.join();
    engine_.WaitForAll();
    std::lock_guard<std::mutex> lk(m_);
    for (auto& kv : done_) delete kv.second;
    done_.clear();
    stop_ = false;
    reader_eof_ = false;
    error_.clear();  // a failed epoch must not poison the next one
    in_flight_ = 0;
    next_out_ = next_seq_ = 0;
    std::fseek(f_, 0, SEEK_SET);
    epoch_++;
    StartEpochLocked();
  }

  const PipelineCfg& cfg() const { return cfg_; }

  void FinishSlot(Batch* b) {
    if (b->remaining.fetch_sub(1) == 1) {
      std::lock_guard<std::mutex> lk(m_);
      done_[b->seq] = b;
      cv_out_.notify_all();
    }
  }

  // decode worker failed: record the first error (surfaced at Next) and
  // complete the slot so the batch chain never wedges
  void TaskError(DecodeTask* t, const char* msg) {
    Batch* b = t->batch;
    {
      std::lock_guard<std::mutex> lk(m_);
      if (error_.empty()) error_ = msg;
      cv_out_.notify_all();
    }
    delete t;
    FinishSlot(b);
  }

  // decode + augment one record into its batch slot (runs on the engine)
  void RunTask(DecodeTask* t) {
    const PipelineCfg& c = cfg_;
    const char* p = t->raw.data();
    MXT_CHECK_MSG(t->raw.size() >= sizeof(IRHeader),
                  "record smaller than IRHeader in " + rec_path_);
    IRHeader h;
    std::memcpy(&h, p, sizeof(h));
    size_t off = sizeof(h);
    int lw = c.label_width;
    if (h.flag > 0) {
      // bounds-check the claimed label count before touching the payload
      MXT_CHECK_MSG(off + (size_t)h.flag * sizeof(float) <= t->raw.size(),
                    "corrupt record: label count exceeds record size in " +
                        rec_path_);
      const float* lab = reinterpret_cast<const float*>(p + off);
      for (int i = 0; i < lw; ++i)
        t->batch->label[t->slot * lw + i] =
            (int)h.flag > i ? lab[i] : 0.0f;
      off += h.flag * sizeof(float);
    } else {
      t->batch->label[t->slot * lw] = h.label;
    }

    cv::Mat buf(1, (int)(t->raw.size() - off), CV_8U,
                const_cast<char*>(p + off));
    cv::Mat img = cv::imdecode(
        buf, c.channels == 1 ? cv::IMREAD_GRAYSCALE : cv::IMREAD_COLOR);
    MXT_CHECK_MSG(!img.empty(), "image decode failed in " + rec_path_);
    if (c.channels == 3) cv::cvtColor(img, img, cv::COLOR_BGR2RGB);

    std::mt19937_64 rng(t->rng_seed);
    // resize shorter edge (ref: image_aug_default.cc resize logic)
    int rs = c.resize_short;
    if (rs <= 0 && (img.rows < c.height || img.cols < c.width))
      rs = std::max(c.height, c.width);
    if (rs > 0) {
      double scale = (double)rs / std::min(img.rows, img.cols);
      // clamp BOTH dims to at least the crop size (the min-dimension clamp
      // must apply even when scale == 1.0, e.g. resize equal to the short
      // edge on an image narrower than the crop)
      int nw = std::max(c.width, (int)lround(img.cols * scale));
      int nh = std::max(c.height, (int)lround(img.rows * scale));
      if (nw != img.cols || nh != img.rows)
        cv::resize(img, img, cv::Size(nw, nh), 0, 0,
                   scale < 1.0 ? cv::INTER_AREA : cv::INTER_LINEAR);
    }
    // crop to (height, width): random (train) or center
    int dy = img.rows - c.height, dx = img.cols - c.width;
    int y0, x0;
    if (c.rand_crop) {
      y0 = dy > 0 ? (int)(rng() % (uint64_t)(dy + 1)) : 0;
      x0 = dx > 0 ? (int)(rng() % (uint64_t)(dx + 1)) : 0;
    } else {
      y0 = std::max(0, dy / 2);
      x0 = std::max(0, dx / 2);
    }
    cv::Mat crop = img(cv::Rect(x0, y0, c.width, c.height));
    if (c.rand_mirror && (rng() & 1)) cv::flip(crop, crop, 1);

    const int hw = c.height * c.width, ch = c.channels;
    if (c.normalize) {
      // float32 NCHW, (x - mean) / std — python-iterator parity mode
      float* out = reinterpret_cast<float*>(t->batch->data.data()) +
                   (size_t)t->slot * ch * hw;
      for (int y = 0; y < c.height; ++y) {
        const uint8_t* row = crop.ptr<uint8_t>(y);
        for (int x = 0; x < c.width; ++x)
          for (int k = 0; k < ch; ++k)
            out[k * hw + y * c.width + x] =
                ((float)row[x * ch + k] - cfg_.mean[k]) / cfg_.std[k];
      }
    } else {
      // u8 NHWC straight copy — device-side normalization mode
      uint8_t* out = t->batch->data.data() + (size_t)t->slot * hw * ch;
      for (int y = 0; y < c.height; ++y)
        std::memcpy(out + (size_t)y * c.width * ch, crop.ptr<uint8_t>(y),
                    (size_t)c.width * ch);
    }
    Batch* b = t->batch;
    delete t;
    FinishSlot(b);
  }

 private:
  void LoadIdx(const std::string& idx_path) {
    std::FILE* fi = std::fopen(idx_path.c_str(), "rb");
    MXT_CHECK_MSG(fi != nullptr, "cannot open " + idx_path);
    char line[256];
    while (std::fgets(line, sizeof(line), fi)) {
      const char* tab = std::strchr(line, '\t');
      if (tab) offsets_.push_back((int64_t)atoll(tab + 1));
    }
    std::fclose(fi);
  }

  void StartEpoch() {
    std::lock_guard<std::mutex> lk(m_);
    StartEpochLocked();
  }

  void StartEpochLocked() {
    order_.clear();
    if (cfg_.shuffle) {
      order_.resize(offsets_.size());
      for (size_t i = 0; i < order_.size(); ++i) order_[i] = i;
      std::mt19937_64 rng(cfg_.seed + 0x9e3779b97f4a7c15ull * (epoch_ + 1));
      std::shuffle(order_.begin(), order_.end(), rng);
    }
    reader_ = std::thread([this] {
      // reader errors (corrupt shard: bad magic, truncation) surface as
      // MXNetError from Next(), never std::terminate
      try {
        ReaderLoop();
      } catch (const std::exception& e) {
        std::lock_guard<std::mutex> lk(m_);
        if (error_.empty()) error_ = e.what();
        reader_eof_ = true;
        cv_out_.notify_all();
      }
    });
  }

  bool ReadRecordAt(size_t pos_idx, std::string* out) {
    if (!order_.empty())
      std::fseek(f_, (long)offsets_[order_[pos_idx]], SEEK_SET);
    out->clear();
    for (;;) {
      uint32_t header[2];
      if (std::fread(header, sizeof(uint32_t), 2, f_) < 2) {
        MXT_CHECK_MSG(out->empty(), "truncated chunked record in " + rec_path_);
        return false;
      }
      MXT_CHECK_MSG(header[0] == kMagic, "bad record magic in " + rec_path_);
      uint32_t cflag = header[1] >> kCFlagBits;
      size_t len = header[1] & kLenMask;
      size_t cur = out->size();
      out->resize(cur + len);
      MXT_CHECK_MSG(std::fread(&(*out)[cur], 1, len, f_) == len,
                    "truncated record in " + rec_path_);
      std::fseek(f_, (long)((4 - len % 4) % 4), SEEK_CUR);
      if (cflag == 0 || cflag == 3) return true;
    }
  }

  void ReaderLoop() {
    const PipelineCfg& c = cfg_;
    size_t idx = 0;
    const size_t total = order_.empty() ? (size_t)-1 : order_.size();
    bool eof = false;
    std::mt19937_64 seed_rng(c.seed + epoch_);
    std::vector<std::string> first_records;
    while (!eof) {
      uint64_t seq;
      {
        std::unique_lock<std::mutex> lk(m_);
        cv_space_.wait(lk, [this] {
          return stop_ || in_flight_ < cfg_.prefetch;
        });
        if (stop_) return;
        in_flight_++;
        seq = next_seq_++;
      }
      Batch* b = new Batch;
      b->seq = seq;
      size_t bytes = (size_t)c.batch * c.channels * c.height * c.width *
                     (c.normalize ? sizeof(float) : 1);
      b->data.resize(bytes);
      b->label.assign((size_t)c.batch * c.label_width, 0.0f);
      b->remaining.store(c.batch);
      int filled = 0;
      std::vector<DecodeTask*> tasks;
      tasks.reserve(c.batch);
      for (int s = 0; s < c.batch; ++s) {
        std::string raw;
        bool ok = idx < total && ReadRecordAt(idx, &raw);
        if (ok) {
          ++idx;
          ++filled;
          if ((int)first_records.size() < c.batch)
            first_records.push_back(raw);
        } else {
          eof = true;
          if (filled == 0) {  // nothing left: drop this batch entirely
            std::lock_guard<std::mutex> lk(m_);
            in_flight_--;
            next_seq_--;
            reader_eof_ = true;
            delete b;
            for (auto* t : tasks) delete t;
            cv_out_.notify_all();
            return;
          }
          // pad the tail batch by repeating this epoch's first records
          raw = first_records[s % first_records.size()];
          b->pad++;
        }
        DecodeTask* t = new DecodeTask{this, b, s, std::move(raw),
                                       seed_rng()};
        tasks.push_back(t);
      }
      for (auto* t : tasks)
        engine_.PushAsync(
            [](void* arg) {
              DecodeTask* dt = static_cast<DecodeTask*>(arg);
              try {
                dt->pipe->RunTask(dt);
              } catch (const std::exception& e) {
                dt->pipe->TaskError(dt, e.what());
              }
            },
            t, nullptr, 0, nullptr, 0, 0);
    }
    std::lock_guard<std::mutex> lk(m_);
    reader_eof_ = true;
    cv_out_.notify_all();
  }

  PipelineCfg cfg_;
  std::string rec_path_;
  Engine engine_;
  std::FILE* f_ = nullptr;
  std::vector<int64_t> offsets_;
  std::vector<size_t> order_;
  uint64_t epoch_ = 0;

  std::mutex m_;
  std::condition_variable cv_space_, cv_out_;
  std::map<uint64_t, Batch*> done_;
  uint64_t next_seq_ = 0, next_out_ = 0;
  int in_flight_ = 0;
  bool stop_ = false;
  bool reader_eof_ = false;
  std::string error_;
  std::thread reader_;
};

}  // namespace mxt

// ---------------------------------------------------------------------------
// C ABI (ctypes-consumed, like the rest of src/)
// ---------------------------------------------------------------------------

extern "C" {

const char* MXImageGetLastError() { return mxt::LastError().c_str(); }

int MXImagePipelineCreate(const char* rec_path, const char* idx_path,
                          const char* cfg, void** out) {
  MXT_API_BEGIN();
  *out = new mxt::ImagePipeline(rec_path, idx_path ? idx_path : "", cfg);
  MXT_API_END();
}

// returns the next batch; *out_batch NULL at epoch end.  data/label point
// into the batch object — valid until MXImagePipelineReleaseBatch.
int MXImagePipelineNext(void* h, void** out_batch, const uint8_t** out_data,
                        const float** out_label, int* out_pad) {
  MXT_API_BEGIN();
  mxt::Batch* b = static_cast<mxt::ImagePipeline*>(h)->Next();
  *out_batch = b;
  if (b) {
    *out_data = b->data.data();
    *out_label = b->label.data();
    *out_pad = b->pad;
  } else {
    *out_data = nullptr;
    *out_label = nullptr;
    *out_pad = 0;
  }
  MXT_API_END();
}

int MXImagePipelineReleaseBatch(void* batch) {
  MXT_API_BEGIN();
  delete static_cast<mxt::Batch*>(batch);
  MXT_API_END();
}

int MXImagePipelineReset(void* h) {
  MXT_API_BEGIN();
  static_cast<mxt::ImagePipeline*>(h)->Reset();
  MXT_API_END();
}

int MXImagePipelineFree(void* h) {
  MXT_API_BEGIN();
  delete static_cast<mxt::ImagePipeline*>(h);
  MXT_API_END();
}

}  // extern "C"
