"""Testing utilities — the framework's numeric-verification backbone.

TPU-native counterpart of the reference's ``python/mxnet/test_utils.py``:
``assert_almost_equal``, ``check_numeric_gradient`` (finite differences vs
autograd), ``check_consistency`` (cross-context: cpu vs tpu — the
reference's cpu-vs-gpu pattern, SURVEY.md §4), ``rand_ndarray``,
``default_context``.

Functions accept either a python callable over NDArrays or a
``symbol.Symbol`` (duck-typed), mirroring the reference where these helpers
operate on Symbols.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Union

import numpy as np

from . import ndarray as nd
from .context import Context, cpu, current_context
from .ndarray import NDArray

__all__ = [
    "list_gpus",
    "default_context", "set_default_context", "assert_almost_equal",
    "almost_equal", "same", "rand_ndarray", "rand_shape_2d", "rand_shape_3d",
    "rand_shape_nd", "check_numeric_gradient", "check_symbolic_forward",
    "check_symbolic_backward", "check_consistency", "simple_forward",
    "default_rtol_atol",
]

_DEFAULT_CTX: Optional[Context] = None


def list_gpus():
    """ref: test_utils.list_gpus — accelerator ordinals.  Here the
    accelerators are TPU chips; returns their local indices (empty on a
    CPU-only backend) so `if mx.test_utils.list_gpus():` gates work."""
    from .context import num_gpus

    return list(range(num_gpus()))


def default_context() -> Context:
    """Test context; override with MXNET_TEST_DEFAULT_CONTEXT=tpu|cpu
    (ref: test_utils.default_context)."""
    global _DEFAULT_CTX
    if _DEFAULT_CTX is not None:
        return _DEFAULT_CTX
    from .util import env

    name = env.get_str("MXNET_TEST_DEFAULT_CONTEXT")
    if name.startswith("tpu"):
        from .context import tpu

        return tpu()
    if name.startswith("cpu"):
        return cpu()
    return current_context()


def set_default_context(ctx: Context):
    global _DEFAULT_CTX
    _DEFAULT_CTX = ctx


def default_rtol_atol(dtype) -> tuple:
    dt = np.dtype(str(dtype)) if str(dtype) != "bfloat16" else None
    if dt is None or str(dtype) == "bfloat16":
        return 1e-1, 1e-1
    if dt == np.float16:
        return 1e-2, 1e-2
    if dt == np.float32:
        return 1e-4, 1e-5
    return 1e-6, 1e-7


def _as_numpy(x):
    if isinstance(x, NDArray):
        x = x.asnumpy()
    x = np.asarray(x)
    if x.dtype.kind == "V" or "bfloat16" in str(x.dtype):  # ml_dtypes bfloat16
        x = x.astype(np.float32)
    return x


def same(a, b) -> bool:
    return np.array_equal(_as_numpy(a), _as_numpy(b))


def almost_equal(a, b, rtol=None, atol=None, equal_nan=False) -> bool:
    a, b = _as_numpy(a), _as_numpy(b)
    rtol = 1e-5 if rtol is None else rtol
    atol = 1e-20 if atol is None else atol
    return np.allclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan)


def assert_almost_equal(a, b, rtol=None, atol=None, names=("a", "b"),
                        equal_nan=False):
    """ref: test_utils.assert_almost_equal — with max-violation reporting."""
    a_np, b_np = _as_numpy(a), _as_numpy(b)
    rtol = 1e-5 if rtol is None else rtol
    atol = 1e-20 if atol is None else atol
    if a_np.shape != b_np.shape:
        raise AssertionError(
            f"shape mismatch: {names[0]}{a_np.shape} vs {names[1]}{b_np.shape}")
    if np.allclose(a_np, b_np, rtol=rtol, atol=atol, equal_nan=equal_nan):
        return
    diff = np.abs(a_np.astype(np.float64) - b_np.astype(np.float64))
    denom = np.abs(b_np.astype(np.float64)) + atol / max(rtol, 1e-300)
    with np.errstate(divide="ignore", invalid="ignore"):
        rel = diff / np.maximum(denom, 1e-300)
    idx = np.unravel_index(np.nanargmax(rel), rel.shape)
    raise AssertionError(
        f"{names[0]} and {names[1]} differ beyond rtol={rtol} atol={atol}: "
        f"max rel err {rel[idx]:.3e} at {idx}: "
        f"{names[0]}={a_np[idx]!r} {names[1]}={b_np[idx]!r}")


# --------------------------------------------------------------------------
# random data helpers (ref: rand_ndarray / rand_shape_*)
# --------------------------------------------------------------------------

def rand_shape_2d(dim0=10, dim1=10):
    return (np.random.randint(1, dim0 + 1), np.random.randint(1, dim1 + 1))


def rand_shape_3d(dim0=10, dim1=10, dim2=10):
    return (np.random.randint(1, dim0 + 1), np.random.randint(1, dim1 + 1),
            np.random.randint(1, dim2 + 1))


def rand_shape_nd(num_dim, dim=10):
    return tuple(np.random.randint(1, dim + 1, size=num_dim))


def rand_ndarray(shape, ctx=None, dtype="float32", scale=1.0) -> NDArray:
    data = np.random.uniform(-scale, scale, size=shape)
    return nd.array(data, ctx=ctx or default_context(), dtype=dtype)


# --------------------------------------------------------------------------
# forward/backward runners — accept callable or Symbol
# --------------------------------------------------------------------------

def _is_symbol(f) -> bool:
    return hasattr(f, "list_arguments") and hasattr(f, "bind")


def _normalize_location(f, location):
    """location: list of arrays or dict name->array (Symbol only)."""
    if isinstance(location, dict):
        if not _is_symbol(f):
            raise ValueError("dict locations require a Symbol")
        names = f.list_arguments()
        missing = [n for n in names if n not in location]
        if missing:
            raise KeyError(f"location is missing arguments {missing} "
                           f"required by symbol (has {sorted(location)})")
        return [location[n] for n in names], names
    return list(location), None


def _to_ndarrays(arrays, ctx, dtype=None):
    out = []
    for a in arrays:
        if isinstance(a, NDArray):
            out.append(a.as_in_context(ctx))
        else:
            out.append(nd.array(a, ctx=ctx, dtype=dtype or "float32"))
    return out


def _run_forward(f, args: List[NDArray], train: bool = False):
    """Returns list of output NDArrays."""
    if _is_symbol(f):
        ex = f.bind(args[0].ctx, args)
        outs = ex.forward(is_train=train)
        return list(outs), ex
    out = f(*args)
    if isinstance(out, (tuple, list)):
        return list(out), None
    return [out], None


def simple_forward(f, *inputs, ctx=None):
    """Run ``f`` on numpy/NDArray inputs, return numpy output(s)."""
    ctx = ctx or default_context()
    args = _to_ndarrays(list(inputs), ctx)
    outs, _ = _run_forward(f, args)
    res = [o.asnumpy() for o in outs]
    return res[0] if len(res) == 1 else res


def check_symbolic_forward(f, location, expected, rtol=1e-5, atol=None,
                           ctx=None, dtype="float32"):
    """Forward result vs numpy oracle (ref: check_symbolic_forward)."""
    ctx = ctx or default_context()
    loc, _ = _normalize_location(f, location)
    args = _to_ndarrays(loc, ctx, dtype)
    outs, _ = _run_forward(f, args)
    if not isinstance(expected, (list, tuple)):
        expected = [expected]
    for o, e in zip(outs, expected):
        assert_almost_equal(o, e, rtol=rtol, atol=atol,
                            names=("forward", "expected"))


def check_symbolic_backward(f, location, out_grads, expected_grads,
                            rtol=1e-5, atol=None, ctx=None, dtype="float32"):
    """Autograd grads vs analytic expectation (ref: check_symbolic_backward)."""
    from . import autograd

    ctx = ctx or default_context()
    loc, _ = _normalize_location(f, location)
    args = _to_ndarrays(loc, ctx, dtype)
    for a in args:
        a.attach_grad()
    with autograd.record():
        outs, _ = _run_forward(f, args, train=True)
        head = outs[0]
    og = out_grads[0] if isinstance(out_grads, (list, tuple)) else out_grads
    og = og if isinstance(og, NDArray) else nd.array(og, ctx=ctx, dtype=dtype)
    head.backward(og)
    if not isinstance(expected_grads, (list, tuple)):
        expected_grads = [expected_grads]
    for a, e in zip(args, expected_grads):
        if e is None:
            continue
        assert_almost_equal(a.grad, e, rtol=rtol, atol=atol,
                            names=("grad", "expected_grad"))


def check_numeric_gradient(f, location, numeric_eps=1e-3,
                           rtol=1e-2, atol=None, ctx=None, dtype="float32",
                           grad_nodes: Optional[Sequence[int]] = None):
    """Finite-difference gradient check vs the autograd tape.

    ref: test_utils.check_numeric_gradient — central differences on a random
    scalar projection of the output; the single most important correctness
    tool in the reference's test suite (SURVEY.md §4).

    Note: runs in ``dtype`` (default float32 — TPU backends have no x64), so
    default eps is looser than the reference's 1e-4.
    """
    from . import autograd

    ctx = ctx or default_context()
    if str(dtype) == "float64":
        dtype = "float32"  # no x64 on TPU-typed backends
    loc, _ = _normalize_location(f, location)
    args_np = [np.asarray(a.asnumpy() if isinstance(a, NDArray) else a,
                          dtype=np.float64) for a in loc]
    argnums = list(grad_nodes) if grad_nodes is not None else list(range(len(args_np)))

    # random projection makes the output scalar: L = sum(out * proj)
    args = _to_ndarrays(args_np, ctx, dtype)
    for i in argnums:
        args[i].attach_grad()
    head_outs, _ = _run_forward(f, args)  # un-recorded: only shape is needed
    proj_np = np.random.normal(0, 1.0, size=head_outs[0].shape).astype(dtype)
    proj = nd.array(proj_np, ctx=ctx)
    with autograd.record():
        outs, _ = _run_forward(f, args, train=True)
        loss = (outs[0] * proj).sum()
    loss.backward()
    sym_grads = {i: args[i].grad.asnumpy().astype(np.float64) for i in argnums}

    def _loss_at(vals: List[np.ndarray]) -> float:
        a = _to_ndarrays(vals, ctx, dtype)
        outs, _ = _run_forward(f, a)
        return float((_as_numpy(outs[0]).astype(np.float64) *
                      proj_np.astype(np.float64)).sum())

    for i in argnums:
        num_grad = np.zeros_like(args_np[i])
        flat = args_np[i].reshape(-1)
        num_flat = num_grad.reshape(-1)
        for j in range(flat.size):
            orig = flat[j]
            flat[j] = orig + numeric_eps
            fp = _loss_at(args_np)
            flat[j] = orig - numeric_eps
            fm = _loss_at(args_np)
            flat[j] = orig
            num_flat[j] = (fp - fm) / (2 * numeric_eps)
        assert_almost_equal(sym_grads[i], num_grad, rtol=rtol,
                            atol=atol if atol is not None else 1e-2,
                            names=(f"autograd_grad[{i}]", f"numeric_grad[{i}]"))


def check_consistency(f, ctx_list: Sequence[Context], location,
                      rtol=1e-4, atol=1e-5, grad: bool = True):
    """Run the same computation on several contexts and compare — the
    reference's cpu-vs-gpu `check_consistency`, here cpu-vs-tpu
    (ref: tests/python/gpu/test_operator_gpu.py pattern)."""
    from . import autograd

    loc_np = [np.asarray(a.asnumpy() if isinstance(a, NDArray) else a)
              for a in location]
    loc_np = [a.astype(np.float32) if a.dtype == np.float64 else a
              for a in loc_np]
    results, grads = [], []
    for ctx in ctx_list:
        args = [nd.array(a, ctx=ctx) for a in loc_np]
        if grad:
            for a in args:
                if np.issubdtype(np.dtype(str(a.data.dtype)), np.floating):
                    a.attach_grad()
            with autograd.record():
                outs, _ = _run_forward(f, args, train=True)
                loss = outs[0].sum()
            loss.backward()
            grads.append([a.grad.asnumpy() if a.grad is not None else None
                          for a in args])
        else:
            outs, _ = _run_forward(f, args)
        results.append([_as_numpy(o) for o in outs])
    ref_out, ref_grad = results[0], grads[0] if grad else None
    for k in range(1, len(ctx_list)):
        for a, b in zip(ref_out, results[k]):
            assert_almost_equal(a, b, rtol=rtol, atol=atol,
                                names=(str(ctx_list[0]), str(ctx_list[k])))
        if grad:
            for a, b in zip(ref_grad, grads[k]):
                if a is None or b is None:
                    continue
                assert_almost_equal(a, b, rtol=rtol, atol=atol,
                                    names=(f"{ctx_list[0]}_grad",
                                           f"{ctx_list[k]}_grad"))
