"""Cache keys for the persistent compile cache.

A :class:`CacheKey` names one executable *semantically*: everything
that could change what XLA would build must be part of the digest, and
nothing else.  The digest covers

  * the caller's structured ``parts`` — avals/treedef reprs, static
    config, donation spec, bucket, device string (each call site
    documents its own tuple);
  * the **lowered program text** (StableHLO) when the caller provides
    it — the strongest signal: two sites that lower to the same module
    share an entry, and any semantic change to the traced program
    (a new op implementation, a jax lowering change) invalidates the
    entry even when the structured parts are unchanged;
  * the environment fingerprint: jax/jaxlib versions plus backend
    platform and device kind.  A cache directory shared across a
    heterogeneous fleet (or across an upgrade) never serves a stale
    executable — the digest simply misses.

Digests are content addresses: the disk store names each entry
``<digest>.mxcc``, so two processes that race to warm the same program
write equivalent entries to the same name (same payload, per-writer
header timestamp) and ``os.replace`` resolves the race to either
copy — both verify.
"""
from __future__ import annotations

import hashlib
import threading
from typing import Any, Optional, Tuple

__all__ = ["CacheKey", "cache_key", "env_fingerprint"]

_FP_LOCK = threading.Lock()
_FP: Optional[Tuple[str, ...]] = None


def env_fingerprint() -> Tuple[str, ...]:
    """(framework, jax, jaxlib, platform, device_kind) — the portion of
    the digest that pins an entry to one software + hardware
    generation.  Computed once per process (the backend cannot change
    after jax init).  The framework version matters because ALIAS keys
    deliberately omit the lowered program text: a code change that
    alters what a site lowers is invisible to them, so every release
    invalidates the whole store (a warm-up re-run, not a correctness
    risk)."""
    global _FP
    if _FP is None:
        with _FP_LOCK:
            if _FP is None:
                import os
                import sys

                import jax
                import jaxlib

                from .. import __version__ as _mx_version

                dev = jax.devices()[0]
                _FP = (f"mxnet_tpu={_mx_version}",
                       f"jax={jax.__version__}",
                       f"jaxlib={jaxlib.__version__}",
                       # exec-tier payloads are pickles: a cache dir
                       # shared across interpreter versions must miss,
                       # not quarantine-thrash on unpicklable entries
                       f"python={sys.version_info.major}."
                       f"{sys.version_info.minor}",
                       f"platform={dev.platform}",
                       f"device_kind={dev.device_kind}",
                       # serialized executables embed the device
                       # assignment: same-kind hosts with different
                       # visible device counts must miss, not trade
                       # mutually-unloadable entries
                       f"devices={len(jax.devices())}",
                       # compile-configuration inputs that change the
                       # BUILT code without changing the StableHLO
                       # text (jax's own persistent cache keys on its
                       # compile options for the same reason)
                       f"xla_flags={os.environ.get('XLA_FLAGS', '')}",
                       f"libtpu={os.environ.get('LIBTPU_INIT_ARGS', '')}",
                       f"matmul_precision="
                       f"{jax.config.jax_default_matmul_precision}")
    return _FP


def first_party(module_name) -> bool:
    """Whether ``module_name`` lives inside this package.  The
    alias-eligibility policy: only first-party code — whose changes
    bump the framework version in :func:`env_fingerprint` — may use
    the cheap (program-text-free) alias keys; user code (custom ops,
    Optimizer subclasses) must always key by the lowered program."""
    mod = module_name or ""
    return mod == "mxnet_tpu" or mod.startswith("mxnet_tpu.")


def _canon(v: Any) -> str:
    """Stable text form of one key part.  Tuples/lists/dicts recurse so
    nesting order is explicit; everything else goes through ``repr``,
    which is deterministic for the part types call sites use (str, int,
    class objects, PyTreeDef, aval tuples)."""
    if isinstance(v, (tuple, list)):
        return "(" + ",".join(_canon(x) for x in v) + ")"
    if isinstance(v, dict):
        return "{" + ",".join(
            f"{_canon(k)}:{_canon(x)}" for k, x in sorted(
                v.items(), key=lambda kv: repr(kv[0]))) + "}"
    if isinstance(v, bytes):
        return "b" + hashlib.sha256(v).hexdigest()
    return repr(v)


class CacheKey:
    """One executable's identity.  ``site`` is a stable family name
    (``serving.bucket``, ``optimizer.fused_step``, ``ops.jit``) kept in
    the entry header for operability — it is part of the digest too, so
    two sites never collide even on identical programs (their calling
    conventions may differ).

    ``components`` is an optional NAMED view of the same identity
    (``{"avals": ..., "statics": ..., "donation": ...}``) consumed by
    the compile-provenance layer (telemetry.mxtriage.provenance): a
    cache miss diffs these against the nearest prior signature at the
    same site so the recorded reason can say *which component* changed.
    It never feeds the digest — ``parts`` (plus program text and the
    env fingerprint) remain the sole identity."""

    __slots__ = ("site", "parts", "program_text", "components",
                 "_digest")

    def __init__(self, site: str, parts: Tuple,
                 program_text: Optional[str] = None,
                 components: Optional[dict] = None):
        self.site = site
        self.parts = parts
        self.program_text = program_text
        self.components = components
        self._digest: Optional[str] = None

    def component_digests(self) -> "dict[str, str]":
        """Per-component content digests for provenance diffing.  The
        named ``components`` when the call site provided them, else
        positional ``part<i>`` names; the env fingerprint always rides
        as ``env`` and the lowered program (when present) as
        ``program`` — both are real miss causes (an upgrade, a code
        change) a diff must be able to name."""
        comps = dict(self.components) if self.components else {
            f"part{i}": p for i, p in enumerate(self.parts)}
        out = {name: hashlib.sha256(_canon(v).encode()).hexdigest()
               for name, v in comps.items()}
        out["env"] = hashlib.sha256(
            "\x1f".join(env_fingerprint()).encode()).hexdigest()
        if self.program_text is not None:
            out["program"] = hashlib.sha256(
                self.program_text.encode()).hexdigest()
        return out

    @property
    def digest(self) -> str:
        """sha256 hex over site + parts + program text + environment
        fingerprint.  Computed once (program text can be megabytes)."""
        if self._digest is None:
            h = hashlib.sha256()
            h.update(self.site.encode())
            h.update(b"\x00")
            h.update(_canon(self.parts).encode())
            h.update(b"\x00")
            h.update("\x1f".join(env_fingerprint()).encode())
            h.update(b"\x00")
            if self.program_text is not None:
                h.update(self.program_text.encode())
            self._digest = h.hexdigest()
        return self._digest

    def __repr__(self):
        return f"CacheKey(site={self.site!r}, digest={self.digest[:12]}...)"


def cache_key(site: str, parts: Tuple,
              program_text: Optional[str] = None,
              components: Optional[dict] = None) -> CacheKey:
    """Build a :class:`CacheKey` (the one constructor call sites use)."""
    return CacheKey(site, tuple(parts), program_text,
                    components=components)
