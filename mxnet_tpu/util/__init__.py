"""Framework-internal utilities (knob registry, shared helpers)."""
from . import env

__all__ = ["env"]
