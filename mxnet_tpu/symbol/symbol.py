"""Symbol: the symbolic (declarative) frontend.

TPU-native counterpart of the reference's Symbol/nnvm graph layer
(ref: python/mxnet/symbol/symbol.py, 3rdparty/tvm/nnvm — Node/NodeEntry/
Graph/Symbol, compose, InferShape, SaveJSON/LoadJSON).

Design (idiomatic TPU, not a port): a Symbol is a lightweight DAG over the
same pure-jax op registry the imperative path uses.  There is no separate
graph IR with memory-planning passes — binding a Symbol compiles the WHOLE
graph into one jitted XLA program (SURVEY.md §7: "graph path becomes
trace → one jitted XLA program"); XLA does fusion, memory planning and
layout.  MXNet conveniences are preserved:

  * auto-created parameter variables (``sym.FullyConnected(x, num_hidden=5,
    name='fc1')`` creates ``fc1_weight``/``fc1_bias``),
  * auxiliary states (BatchNorm moving stats),
  * bidirectional ``infer_shape`` (data shape in → weight shapes out) via
    per-op parameter-shape rules + ``jax.eval_shape`` forward propagation,
  * nnvm-style JSON save/load (``prefix-symbol.json`` files).
"""
from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..base import MXNetError
from ..ops.registry import get_op

__all__ = ["Symbol", "var", "Variable", "Group", "load", "load_json"]


class _Node:
    """One graph node: a variable (op=None) or an op application."""

    __slots__ = ("op", "name", "attrs", "inputs", "num_outputs", "is_aux",
                 "shape_hint", "__weakref__")

    def __init__(self, op: Optional[str], name: str, attrs: Dict[str, Any],
                 inputs: List[Tuple["_Node", int]], num_outputs: int = 1,
                 is_aux: bool = False, shape_hint=None):
        self.op = op
        self.name = name
        self.attrs = attrs
        self.inputs = inputs
        self.num_outputs = num_outputs
        self.is_aux = is_aux
        self.shape_hint = tuple(shape_hint) if shape_hint else None


class _NameCounter:
    """Delegates to the active mx.name.NameManager (supports Prefix)."""

    @staticmethod
    def next(hint: str) -> str:
        from ..name import current

        return current().get(None, hint)


_NAMER = _NameCounter()


# --------------------------------------------------------------------------
# Op schemas: named array inputs, aux inputs, auto-created-parameter shape
# rules.  This plays the role of nnvm's FListInputNames +
# FInferShape-for-parameters (ref: src/operator/nn/*-inl.h InferShape).
# --------------------------------------------------------------------------

def _fc_shapes(ins, attrs):
    d = ins.get("data")
    if d is None:
        return {}
    flat = attrs.get("flatten", True)
    in_dim = int(np.prod(d[1:])) if flat else d[-1]
    nh = attrs["num_hidden"]
    return {"weight": (nh, in_dim), "bias": (nh,)}


def _conv_shapes(ins, attrs):
    d = ins.get("data")
    if d is None:
        return {}
    nf = attrs["num_filter"]
    g = attrs.get("num_group", 1)
    k = tuple(attrs.get("kernel", ()))
    return {"weight": (nf, d[1] // g) + k, "bias": (nf,)}


def _deconv_shapes(ins, attrs):
    d = ins.get("data")
    if d is None:
        return {}
    nf = attrs["num_filter"]
    g = attrs.get("num_group", 1)
    k = tuple(attrs.get("kernel", ()))
    return {"weight": (d[1], nf // g) + k, "bias": (nf,)}


def _chan_shapes(ins, attrs):
    d = ins.get("data")
    if d is None:
        return {}
    ax = attrs.get("axis", 1)
    c = (d[ax],)
    return {k: c for k in ("gamma", "beta", "moving_mean", "moving_var")}


def _lastdim_shapes(ins, attrs):
    d = ins.get("data")
    if d is None:
        return {}
    ax = attrs.get("axis", -1)
    c = (d[ax],)
    return {"gamma": c, "beta": c}


def _embed_shapes(ins, attrs):
    return {"weight": (attrs["input_dim"], attrs["output_dim"])}


def _label_shapes(ins, attrs):
    d = ins.get("data")
    if d is None:
        return {}
    return {"label": tuple(d[:-1])}


def _rnn_shapes(ins, attrs):
    d = ins.get("data")  # (T, N, I)
    if d is None:
        return {}
    from ..ops.rnn import rnn_param_size

    if not attrs.get("state_size"):
        raise MXNetError("RNN requires a positive state_size attribute")
    return {"parameters": (rnn_param_size(
        attrs.get("mode", "lstm"), d[2], attrs["state_size"],
        attrs.get("num_layers", 1), attrs.get("bidirectional", False)),)}


class _Schema:
    def __init__(self, inputs: Sequence[str], aux: Sequence[str] = (),
                 optional: Sequence[str] = (), param_shapes=None,
                 label_suffix: Optional[str] = None):
        self.inputs = tuple(inputs)          # named graph inputs, in order
        self.aux = frozenset(aux)            # subset that are aux states
        self.optional = frozenset(optional)  # skipped when absent (no_bias)
        self.param_shapes = param_shapes
        self.label_suffix = label_suffix     # label vars named without prefix


SCHEMAS: Dict[str, _Schema] = {
    "FullyConnected": _Schema(("data", "weight", "bias"), optional=("bias",),
                              param_shapes=_fc_shapes),
    "Convolution": _Schema(("data", "weight", "bias"), optional=("bias",),
                           param_shapes=_conv_shapes),
    "Deconvolution": _Schema(("data", "weight", "bias"), optional=("bias",),
                             param_shapes=_deconv_shapes),
    "BatchNorm": _Schema(("data", "gamma", "beta", "moving_mean", "moving_var"),
                         aux=("moving_mean", "moving_var"),
                         param_shapes=_chan_shapes),
    "LayerNorm": _Schema(("data", "gamma", "beta"),
                         param_shapes=_lastdim_shapes),
    "InstanceNorm": _Schema(("data", "gamma", "beta"),
                            param_shapes=_chan_shapes),
    "GroupNorm": _Schema(("data", "gamma", "beta"),
                         param_shapes=_chan_shapes),
    "RMSNorm": _Schema(("data", "gamma"), param_shapes=_lastdim_shapes),
    "Embedding": _Schema(("data", "weight"), param_shapes=_embed_shapes),
    "Dropout": _Schema(("data",)),  # PRNG key injected by the executor
    "SoftmaxOutput": _Schema(("data", "label"), label_suffix="label",
                             param_shapes=_label_shapes),
    "LeakyReLU": _Schema(("data", "gamma"), optional=("gamma",)),
    "RNN": _Schema(("data", "parameters", "state", "state_cell"),
                   optional=("state", "state_cell"),
                   param_shapes=_rnn_shapes),
}

# ops whose kernels consult the train flag; the executor passes _train
TRAIN_AWARE_OPS = {"BatchNorm", "Dropout", "RNN"}
# ops that consume a PRNG key injected at execution time (as `key=` —
# its positional slot differs per op)
KEYED_OPS = {"Dropout", "RNN"}


def _is_sym(x) -> bool:
    return isinstance(x, Symbol)


def _str_attrs(node):
    """One attr-stringification rule for list_attr/attr_dict/tojson."""
    return {k: str(v) for k, v in node.attrs.items()}


class Symbol:
    """An entry (or group of entries) into the symbolic graph."""

    __slots__ = ("_heads",)

    def __init__(self, heads: List[Tuple[_Node, int]]):
        self._heads = heads

    # ---- identity --------------------------------------------------------
    @property
    def name(self) -> str:
        if len(self._heads) == 1:
            return self._heads[0][0].name
        return "group"

    def __repr__(self):
        return f"<Symbol {self.name}>"

    def __iter__(self):
        for i in range(len(self._heads)):
            yield self[i]

    def __len__(self):
        return len(self._heads)

    def __getitem__(self, idx):
        if isinstance(idx, str):
            for i, nm in enumerate(self.list_outputs()):
                if nm == idx:
                    return Symbol([self._heads[i]])
            raise MXNetError(f"no output named {idx!r}")
        return Symbol([self._heads[idx]])

    def attr(self, key):
        return self._heads[0][0].attrs.get(key)

    def list_attr(self):
        """This node's string attrs (ref: Symbol.list_attr)."""
        return _str_attrs(self._heads[0][0])

    def attr_dict(self):
        """{node_name: {attr: value}} over the whole graph
        (ref: Symbol.attr_dict)."""
        return {node.name: _str_attrs(node) for node in self._topo()
                if node.attrs}

    def debug_str(self):
        """Readable graph dump (ref: Symbol.debug_str over nnvm)."""
        lines = []
        for node in self._topo():
            op = node.op or "Variable"
            ins = ", ".join(getattr(i[0], "name", "?") for i in node.inputs)
            lines.append(f"{op} {node.name}({ins})")
        return "\n".join(lines)

    # ---- graph traversal -------------------------------------------------
    def _topo(self) -> List[_Node]:
        """Post-order DFS from heads, inputs first (nnvm::DFSVisit order)."""
        seen = set()
        order: List[_Node] = []

        def visit(node: _Node):
            if id(node) in seen:
                return
            seen.add(id(node))
            for (inp, _) in node.inputs:
                visit(inp)
            order.append(node)

        for (n, _) in self._heads:
            visit(n)
        return order

    def list_arguments(self) -> List[str]:
        return [n.name for n in self._topo() if n.op is None and not n.is_aux]

    def list_outputs(self) -> List[str]:
        out = []
        for (n, i) in self._heads:
            if n.num_outputs == 1:
                out.append(f"{n.name}_output")
            else:
                out.append(f"{n.name}_output{i}")
        return out

    def list_auxiliary_states(self) -> List[str]:
        return [n.name for n in self._topo() if n.op is None and n.is_aux]

    def get_internals(self) -> "Symbol":
        heads = []
        for n in self._topo():
            for i in range(n.num_outputs):
                heads.append((n, i))
        return Symbol(heads)

    def get_children(self) -> Optional["Symbol"]:
        node = self._heads[0][0]
        if not node.inputs:
            return None
        return Symbol(list(node.inputs))

    # ---- composition sugar ----------------------------------------------
    def _binary(self, scalar_op, elem_op, other, reverse=False):
        if _is_sym(other):
            a, b = (other, self) if reverse else (self, other)
            return _apply(elem_op, [a, b], {})
        attrs = {"scalar": float(other)}
        return _apply(scalar_op, [self], attrs)

    def __add__(self, o):
        return self._binary("_plus_scalar", "broadcast_add", o)

    def __radd__(self, o):
        return self.__add__(o)

    def __sub__(self, o):
        return self._binary("_minus_scalar", "broadcast_sub", o)

    def __rsub__(self, o):
        if _is_sym(o):
            return self._binary(None, "broadcast_sub", o, reverse=True)
        return _apply("_rminus_scalar", [self], {"scalar": float(o)})

    def __mul__(self, o):
        return self._binary("_mul_scalar", "broadcast_mul", o)

    def __rmul__(self, o):
        return self.__mul__(o)

    def __truediv__(self, o):
        return self._binary("_div_scalar", "broadcast_div", o)

    def __rtruediv__(self, o):
        if _is_sym(o):
            return self._binary(None, "broadcast_div", o, reverse=True)
        return _apply("_rdiv_scalar", [self], {"scalar": float(o)})

    def __pow__(self, o):
        return self._binary("_power_scalar", "broadcast_power", o)

    def __neg__(self):
        return _apply("negative", [self], {})

    # common method sugar (subset of the reference's fluent API)
    def reshape(self, shape):
        return _apply("reshape", [self], {"shape": tuple(shape)})

    def transpose(self, axes=None):
        return _apply("transpose", [self], {"axes": tuple(axes) if axes else None})

    def flatten(self):
        return _apply("flatten", [self], {})

    def sum(self, axis=None, keepdims=False):
        return _apply("sum", [self], {"axis": axis, "keepdims": keepdims})

    def mean(self, axis=None, keepdims=False):
        return _apply("mean", [self], {"axis": axis, "keepdims": keepdims})

    def softmax(self, axis=-1):
        return _apply("softmax", [self], {"axis": axis})

    # ---- shape/type inference -------------------------------------------
    def infer_shape(self, *args, **kwargs):
        """(arg_shapes, out_shapes, aux_shapes); raises on unknowns
        (ref: Symbol.infer_shape over nnvm InferShape pass)."""
        return self._infer_shape_impl(False, *args, **kwargs)

    def infer_shape_partial(self, *args, **kwargs):
        return self._infer_shape_impl(True, *args, **kwargs)

    def _infer_shape_impl(self, partial, *args, **kwargs):
        import jax
        import jax.numpy as jnp

        known: Dict[str, Tuple[int, ...]] = {}
        arg_names = self.list_arguments()
        if args:
            for name, shp in zip(arg_names, args):
                if shp is not None:
                    known[name] = tuple(shp)
        for k, v in kwargs.items():
            known[k] = tuple(v)

        shapes: Dict[Tuple[int, int], Optional[Tuple[int, ...]]] = {}
        var_shapes: Dict[str, Optional[Tuple[int, ...]]] = {}
        topo = self._topo()
        for node in topo:
            if node.op is None:
                shp = known.get(node.name) or node.shape_hint
                var_shapes[node.name] = tuple(shp) if shp else None
                shapes[(id(node), 0)] = var_shapes[node.name]
                continue
            schema = SCHEMAS.get(node.op)
            in_named = {}
            if schema:
                for (inp, idx), nm in zip(node.inputs, schema.inputs):
                    in_named[nm] = shapes.get((id(inp), idx))
                if schema.param_shapes:
                    rules = schema.param_shapes(in_named, node.attrs)
                    for (inp, idx), nm in zip(node.inputs, schema.inputs):
                        if inp.op is None and shapes.get((id(inp), idx)) is None \
                                and nm in rules:
                            var_shapes[inp.name] = tuple(rules[nm])
                            shapes[(id(inp), idx)] = var_shapes[inp.name]
            in_shapes = [shapes.get((id(inp), idx)) for (inp, idx) in node.inputs]
            if any(s is None for s in in_shapes):
                for i in range(node.num_outputs):
                    shapes[(id(node), i)] = None
                continue
            out_structs = _eval_node_shape(node, in_shapes)
            for i in range(node.num_outputs):
                shapes[(id(node), i)] = tuple(out_structs[i].shape)

        arg_shapes = [var_shapes.get(n) for n in arg_names]
        aux_shapes = [var_shapes.get(n) for n in self.list_auxiliary_states()]
        out_shapes = [shapes.get((id(n), i)) for (n, i) in self._heads]
        if not partial:
            missing = [n for n, s in zip(arg_names, arg_shapes) if s is None]
            if missing or any(s is None for s in out_shapes):
                raise MXNetError(
                    f"infer_shape incomplete; unknown arguments: {missing}. "
                    f"Provide their shapes explicitly.")
        return arg_shapes, out_shapes, aux_shapes

    def infer_type(self, *args, **kwargs):
        f32 = np.float32
        return ([f32] * len(self.list_arguments()),
                [f32] * len(self._heads),
                [f32] * len(self.list_auxiliary_states()))

    # ---- serialization (nnvm JSON-compatible layout) --------------------
    def tojson(self) -> str:
        """Reference-format nnvm JSON: nodes carry ONLY op/name/attrs/inputs
        (ref: nnvm Graph SaveJSON — num_outputs / aux-ness / shape hints are
        never stored; loaders re-derive them from op schemas).  Attr values
        are plain strings (``str(v)``) exactly as the reference writes them
        ("(3, 3)", "64", "True", "relu"); shape hints ride in the
        ``__shape__`` attr like reference variable nodes."""
        topo = self._topo()
        index = {id(n): i for i, n in enumerate(topo)}
        nodes = []
        for n in topo:
            attrs = _str_attrs(n)
            if n.op is None and n.shape_hint:
                attrs["__shape__"] = str(tuple(n.shape_hint))
            spec = {
                "op": "null" if n.op is None else n.op,
                "name": n.name,
                "inputs": [[index[id(inp)], idx, 0] for (inp, idx) in n.inputs],
            }
            if attrs:
                spec["attrs"] = attrs
            nodes.append(spec)
        # node_row_ptr: prefix sum of per-node output counts (nnvm IndexedGraph)
        row_ptr = [0]
        for n in topo:
            row_ptr.append(row_ptr[-1] + n.num_outputs)
        return json.dumps({
            "nodes": nodes,
            "arg_nodes": [i for i, n in enumerate(topo) if n.op is None],
            "node_row_ptr": row_ptr,
            "heads": [[index[id(n)], i, 0] for (n, i) in self._heads],
            "attrs": {"mxnet_version": ["int", 10700]},
        }, indent=2)

    def save(self, fname: str):
        with open(fname, "w") as f:
            f.write(self.tojson())

    def bind(self, ctx, args, args_grad=None, grad_req="write",
             aux_states=None, **kwargs):
        from .executor import GraphExecutor

        return GraphExecutor(self, ctx, args, args_grad=args_grad,
                             grad_req=grad_req, aux_states=aux_states)

    def simple_bind(self, ctx, grad_req="write", type_dict=None,
                    **shape_kwargs):
        from .executor import GraphExecutor

        return GraphExecutor.simple_bind(self, ctx, grad_req=grad_req,
                                         **shape_kwargs)

    def eval(self, ctx=None, **kwargs):
        from ..context import current_context

        ctx = ctx or current_context()
        ex = self.bind(ctx, kwargs)
        return ex.forward()


def _eval_node_shape(node: _Node, in_shapes):
    """Output ShapeDtypeStructs for one node via jax.eval_shape."""
    import jax
    import jax.numpy as jnp

    op = get_op(node.op)
    structs = [jax.ShapeDtypeStruct(s, jnp.float32) for s in in_shapes]
    attrs = {k: v for k, v in node.attrs.items() if not k.startswith("__")}
    if node.op in KEYED_OPS:
        key_struct = jax.ShapeDtypeStruct((2,), jnp.uint32)
        out = jax.eval_shape(
            lambda key, *a: op.fn(*a, key=key, **attrs), key_struct,
            *structs)
    else:
        out = jax.eval_shape(lambda *a: op.fn(*a, **attrs), *structs)
    if not isinstance(out, (tuple, list)):
        out = [out]
    return list(out)


# --------------------------------------------------------------------------
# composition
# --------------------------------------------------------------------------

def _scope_attrs(user_attr: Optional[Dict[str, str]] = None) -> Dict[str, Any]:
    """Attributes from the active mx.AttrScope merged with explicit ones,
    stored as __key__ node attrs (ref: AttrScope.get in attribute.py)."""
    from ..attribute import current as _current_scope

    merged = _current_scope().get(user_attr)
    return {f"__{k}__": v for k, v in merged.items()}


def _apply(op_name: str, input_syms: List[Symbol], attrs: Dict[str, Any],
           name: Optional[str] = None) -> Symbol:
    op = get_op(op_name)
    name = name or _NAMER.next(op_name.lower().lstrip("_"))
    attrs = {**attrs, **_scope_attrs()}
    heads: List[Tuple[_Node, int]] = []
    for s in input_syms:
        if len(s._heads) != 1:
            raise MXNetError(
                f"op {op_name} input must be single-output, got group")
        heads.append(s._heads[0])
    try:
        nout = op.nout(attrs)
    except Exception:
        nout = 1
    node = _Node(op_name, name, attrs, heads, num_outputs=nout)
    return Symbol([(node, i) for i in range(nout)]) if nout > 1 \
        else Symbol([(node, 0)])


def make_symbol_function(op_name: str):
    """Build the symbolic wrapper for a registered op (the counterpart of
    the reference's generated symbol functions,
    ref: python/mxnet/symbol/register.py::_make_symbol_function)."""
    import inspect

    op = get_op(op_name)
    schema = SCHEMAS.get(op.name)
    try:
        sig_params = list(inspect.signature(op.fn).parameters)
    except (TypeError, ValueError):
        sig_params = []

    def fn(*args, name: Optional[str] = None, attr=None, **kwargs):
        node_name = name or _NAMER.next(op.name.lower().lstrip("_"))
        sym_inputs: List[Optional[Symbol]] = []
        attrs: Dict[str, Any] = {}

        if schema is not None:
            named: Dict[str, Symbol] = {}
            pos = []
            for a in args:
                if _is_sym(a):
                    pos.append(a)
                else:
                    raise TypeError(
                        f"{op.name}: scalar/tuple parameters must be passed "
                        f"by keyword (got positional {a!r})")
            for i, s in enumerate(pos):
                if i < len(schema.inputs):
                    named[schema.inputs[i]] = s
            for k in list(kwargs):
                if _is_sym(kwargs[k]) and k in schema.inputs:
                    named[k] = kwargs.pop(k)
            attrs = {k: v for k, v in kwargs.items() if not _is_sym(v)}

            def _wanted(nm: str) -> bool:
                # optional inputs are auto-created only when the attrs say
                # the op will use them (bias unless no_bias; PReLU slope)
                if nm not in schema.optional:
                    return True
                if nm == "bias":
                    return not attrs.get("no_bias", False)
                if op.name == "LeakyReLU" and nm == "gamma":
                    return attrs.get("act_type", "leaky") == "prelu"
                return False

            skipped: List[str] = []
            for nm in schema.inputs:
                if nm in named:
                    if skipped:
                        # node.inputs bind POSITIONALLY downstream: a
                        # later optional after a skipped one would
                        # silently land in the wrong slot
                        raise MXNetError(
                            f"{op.name}: input {nm!r} given but earlier "
                            f"optional input(s) {skipped} omitted — "
                            f"pass them explicitly")
                    sym_inputs.append(named[nm])
                elif _wanted(nm):
                    if skipped:
                        raise MXNetError(
                            f"{op.name}: auto-created input {nm!r} "
                            f"follows omitted optional input(s) "
                            f"{skipped} — pass them explicitly")
                    sym_inputs.append(
                        Symbol([(_Node(None, f"{node_name}_{nm}", {}, [],
                                       is_aux=nm in schema.aux), 0)]))
                else:
                    skipped.append(nm)
        else:
            # generic op: positional args map onto the pure fn's signature
            # in order — Symbols become graph inputs, scalars become attrs
            # under the matching parameter name (mx.sym.expand_dims(x, 1)
            # → axis=1), matching the generated-wrapper contract
            slot: Dict[str, Symbol] = {}
            pos = []
            attrs = {}
            for i, a in enumerate(args):
                if _is_sym(a):
                    pos.append(a)
                elif i < len(sig_params):
                    attrs[sig_params[i]] = a
                else:
                    raise TypeError(
                        f"{op.name}: too many positional arguments")
            for k in list(kwargs):
                if _is_sym(kwargs[k]):
                    slot[k] = kwargs.pop(k)
            attrs.update(kwargs)
            if slot:
                ordered = [p for p in sig_params if p in slot]
                pos = pos + [slot[p] for p in ordered]
            sym_inputs = pos

        ins = [s for s in sym_inputs if s is not None]
        heads = []
        for s in ins:
            if len(s._heads) != 1:
                raise MXNetError(f"{op.name}: group symbol not allowed as input")
            heads.append(s._heads[0])
        # typo'd attributes fail at COMPOSITION time, not bind time
        attrs = op.validate_attrs(attrs)
        try:
            nout = op.nout(attrs)
        except Exception:
            nout = 1
        node = _Node(op.name, node_name, attrs, heads, num_outputs=nout)
        node.attrs.update(_scope_attrs(attr))
        return Symbol([(node, i) for i in range(nout)]) if nout > 1 \
            else Symbol([(node, 0)])

    fn.__name__ = op_name
    fn.__qualname__ = op_name
    fn.__doc__ = (f"Symbolic wrapper for registered op '{op_name}'.\n\n"
                  f"{op.param_doc}")
    return fn


# --------------------------------------------------------------------------
# public constructors
# --------------------------------------------------------------------------

def var(name: str, shape=None, init=None, attr=None, dtype=None,
        lr_mult=None, wd_mult=None, stype=None) -> Symbol:
    """Create a symbolic variable (ref: symbol.var / sym.Variable)."""
    attrs = _scope_attrs(attr)
    if init is not None:
        attrs["__init__"] = str(init)
    if lr_mult is not None:
        attrs["__lr_mult__"] = str(lr_mult)
    if wd_mult is not None:
        attrs["__wd_mult__"] = str(wd_mult)
    node = _Node(None, name, attrs, [], shape_hint=shape)
    return Symbol([(node, 0)])


Variable = var


def Group(symbols: Sequence[Symbol]) -> Symbol:
    heads = []
    for s in symbols:
        heads.extend(s._heads)
    return Symbol(heads)


_JSON_LITERALS = {"true": True, "false": False, "null": None}


def _parse_attr_value(v):
    """Reference attrs are strings ("(3, 3)", "64", "True", "relu"); parse
    python literals, fall back to the raw string (the same contract the
    reference's dmlc parameter parser implements per-op).  JSON-spelled
    booleans/null are accepted too — files saved by this library before
    the reference-format switch encoded attrs via json.dumps."""
    if not isinstance(v, str):
        return v
    if v in _JSON_LITERALS:
        return _JSON_LITERALS[v]
    import ast

    try:
        val = ast.literal_eval(v)
    except (ValueError, SyntaxError):
        return v

    def _tuplify(x):  # JSON lists -> tuples (op attrs must be hashable)
        if isinstance(x, list):
            return tuple(_tuplify(i) for i in x)
        return x

    return _tuplify(val)


def load_json(json_str: str) -> Symbol:
    """Load reference-format nnvm JSON.  Nodes carry only op/name/attrs/
    inputs (the genuine ``-symbol.json`` layout — attr key may also be
    ``param``/``attr`` in older files; input entries may be 2- or 3-long);
    num_outputs is re-derived from the op registry and aux-ness from
    consumer schemas, exactly as nnvm re-derives them via FMutateInputs."""
    data = json.loads(json_str)

    nodes: List[_Node] = []
    for spec in data["nodes"]:
        # legacy files split op params ("param") from user attrs ("attr");
        # merge all three spellings, newest key winning
        raw: Dict[str, Any] = {}
        for key in ("param", "attr", "attrs"):
            v = spec.get(key)
            if v:
                raw.update(v)
        attrs = {k: _parse_attr_value(v) for k, v in raw.items()}
        if spec["op"] == "null":
            # legacy pre-reference-format files stored the hint as a
            # top-level node field instead of the __shape__ attr
            shape_hint = attrs.pop("__shape__", None) \
                or spec.get("shape_hint")
            node = _Node(None, spec["name"], attrs, [],
                         shape_hint=shape_hint)
        else:
            inputs = [(nodes[e[0]], e[1]) for e in spec["inputs"]]
            # unknown ops still load (inspection: list_arguments, viz);
            # they fail at bind time like the reference's deferred check
            try:
                nout = get_op(spec["op"]).nout(attrs)
            except Exception:
                nout = 1
            node = _Node(spec["op"], spec["name"], attrs, inputs,
                         num_outputs=nout)
        nodes.append(node)

    # aux-ness is structural: a variable feeding a schema aux slot
    # (e.g. BatchNorm moving_mean/moving_var) is an auxiliary state
    for node in nodes:
        if node.op is None:
            continue
        schema = SCHEMAS.get(node.op)
        if schema is None or not schema.aux:
            continue
        for (inp, _idx), nm in zip(node.inputs, schema.inputs):
            if nm in schema.aux and inp.op is None:
                inp.is_aux = True

    heads = [(nodes[e[0]], e[1]) for e in data["heads"]]
    return Symbol(heads)


def load(fname: str) -> Symbol:
    with open(fname) as f:
        return load_json(f.read())
