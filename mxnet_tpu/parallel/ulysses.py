"""Ulysses-style sequence parallelism — all-to-all over the 'sp' axis.

The second of the two standard long-context layouts (the task's "ring
attention OR all-to-all sequence/context parallelism"; pattern source:
DeepSpeed-Ulysses).  Complements `parallel.ring`:

  * ring: K/V blocks rotate (n-1 ppermute hops), O(L/n) memory per
    device, score matrix never materializes — best for the longest
    sequences.
  * ulysses (this module): ONE all_to_all re-shards [B, H, L/n, D]
    (sequence-sharded) into [B, H/n, L, D] (head-sharded), each device
    runs ordinary full attention for its head group, and one all_to_all
    re-shards back.  Two collectives total instead of n-1 hops, so it
    wins when H >= n and L/n fits memory; it is also the layout that
    composes directly with a head-sharded ('tp') attention projection.

Both are pure-SPMD shard_map bodies, so XLA schedules the all_to_all on
ICI and overlaps it with surrounding compute.
"""
from __future__ import annotations

from typing import Optional

from jax import lax

from ..base import MXNetError
from .ring import local_attention, sharded_seq_attention

__all__ = ["ulysses_attention", "ulysses_attention_sharded"]


def ulysses_attention(q, k, v, axis_name: str = "sp", *,
                      causal: bool = False,
                      scale: Optional[float] = None):
    """Per-shard body: call INSIDE shard_map with q,k,v sequence-sharded
    [B, H, L_local, D] along `axis_name`.  Heads must divide the axis
    size."""
    n = lax.psum(1, axis_name)
    h = q.shape[1]
    # n is static inside shard_map over a concrete mesh axis
    if h % int(n) != 0:
        raise MXNetError(
            f"ulysses_attention needs heads ({h}) divisible by the "
            f"'{axis_name}' axis size ({int(n)}); use parallel.ring for "
            "few-head models")

    def seq_to_head(x):
        # [B, H, L/n, D] -> [B, H/n, L, D]
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    def head_to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    qh, kh, vh = seq_to_head(q), seq_to_head(k), seq_to_head(v)
    out = local_attention(qh, kh, vh, causal=causal, scale=scale)
    return head_to_seq(out)


def ulysses_attention_sharded(q, k, v, **kw):
    """User entry: q,k,v are [B, H, L, D] global arrays; shards batch
    over the data axes and sequence over `axis_name`, re-shards to heads
    with one all_to_all each way."""
    return sharded_seq_attention(
        ulysses_attention, q, k, v,
        entry_name="ulysses_attention_sharded", **kw)
