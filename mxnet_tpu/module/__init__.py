"""Module API (ref: python/mxnet/module/)."""
from .base_module import BaseModule
from .module import Module
from .bucketing_module import BucketingModule
from .executor_group import DataParallelExecutorGroup

__all__ = ["BaseModule", "Module", "BucketingModule",
           "DataParallelExecutorGroup"]
