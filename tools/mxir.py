#!/usr/bin/env python
"""mxir CLI — audit compiled StableHLO programs (rules MX014–MX018).

Offline (no jax import, like mxlint):

    python tools/mxir.py /path/to/compile-cache        # audit a cache dir
    python tools/mxir.py module.mlir --json            # audit one module
    python tools/mxir.py CACHE --out MXIR.json

Walks ``*.mxcc`` entries (the persistent compile cache's on-disk
format), audits every ``stablehlo``-tier payload, and renders the
MXLINT-shaped MXIR.json report.  Entries that fail to decode or parse
are counted as ``parse_skipped`` — never fatal.  Exit status: 0 when
no violations, 1 when any program has findings.

Selftest (imports the framework; drives real compiles):

    python tools/mxir.py --selftest --out MXIR.json

Runs the full known-answer gate: per-rule seeded/clean fixture pairs,
the PR 18 gather-replication case lowered live and caught as MX015,
an MXNET_IR_AUDIT=1 audit of real fused + SPMD step programs (must be
clean), the static wire-bytes model cross-checked against the measured
``mx_collective_wire_bytes_total`` int8 lane (MXNET_IR_WIRE_TOL), and
the audit-off overhead guard (<= 3% of a fused step).  Writes the
stage results plus the live report with a top-level ``gate_ok``.
"""
from __future__ import annotations

import argparse
import hashlib
import importlib.util
import json
import os
import struct
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_MXCC_MAGIC = b"MXCC1\n"


def _load_analysis():
    """Load mxnet_tpu.analysis standalone (no mxnet_tpu/__init__.py,
    no jax) — same idiom as tools/mxlint.py."""
    if "mxnet_tpu.analysis" in sys.modules:
        return sys.modules["mxnet_tpu.analysis"]
    pkg_dir = os.path.join(_REPO, "mxnet_tpu", "analysis")
    spec = importlib.util.spec_from_file_location(
        "mxnet_tpu.analysis", os.path.join(pkg_dir, "__init__.py"),
        submodule_search_locations=[pkg_dir])
    mod = importlib.util.module_from_spec(spec)
    sys.modules["mxnet_tpu.analysis"] = mod
    spec.loader.exec_module(mod)
    return mod


def _decode_mxcc(path: str):
    """Minimal reader for one ``.mxcc`` entry: (header, payload).
    Raises ValueError on any structural problem (the caller counts it
    as a skip — offline audit never quarantines, that is the runtime
    store's job)."""
    with open(path, "rb") as f:
        blob = f.read()
    if not blob.startswith(_MXCC_MAGIC):
        raise ValueError("bad magic (not a compile-cache entry)")
    off = len(_MXCC_MAGIC)
    if len(blob) < off + 4:
        raise ValueError("truncated header length")
    (hlen,) = struct.unpack(">I", blob[off:off + 4])
    off += 4
    hjson = blob[off:off + hlen]
    if len(hjson) != hlen:
        raise ValueError("truncated header")
    try:
        header = json.loads(hjson)
    except ValueError as e:
        raise ValueError(f"unparseable header: {e}")
    payload = blob[off + hlen:]
    want = header.get("payload_sha256")
    if want and hashlib.sha256(payload).hexdigest() != want:
        raise ValueError("payload sha256 mismatch")
    return header, payload


def _audit_offline(analysis, target: str, repl_bytes: int):
    """Audit a cache directory (``*.mxcc``) or a single module file.
    Returns ``(audits, alias_skipped)`` — the ProgramAudits plus the
    count of exec/alias-tier entries skipped for carrying no module
    text."""
    audits = []
    alias_skipped = 0

    def one(site: str, text: str):
        try:
            module = analysis.parse_module(text)
            violations = analysis.audit_module(
                text, site=site, repl_bytes=repl_bytes, module=module)
            est = analysis.estimate_wire_bytes(module)
            audits.append(analysis.ProgramAudit(
                site=site, violations=violations,
                wire={"total": est.total, "by_lane": est.by_lane,
                      "legs": len(est.legs),
                      "unknown_transitions": est.unknown_transitions}))
        except analysis.IrParseError as e:
            audits.append(analysis.ProgramAudit(site=site,
                                                parse_error=str(e)))
        except Exception as e:  # noqa: BLE001 — offline audit never dies
            audits.append(analysis.ProgramAudit(
                site=site, parse_error=f"{type(e).__name__}: {e}"))

    if os.path.isdir(target):
        for name in sorted(os.listdir(target)):
            if not name.endswith(".mxcc"):
                continue
            path = os.path.join(target, name)
            site = name[:-len(".mxcc")]
            try:
                header, payload = _decode_mxcc(path)
            except (OSError, ValueError) as e:
                audits.append(analysis.ProgramAudit(
                    site=site, parse_error=f"undecodable entry: {e}"))
                continue
            if header.get("tier") != "stablehlo":
                # exec/alias tiers carry no module text; COUNTED so the
                # artifact says how much of the cache went unaudited
                alias_skipped += 1
                continue
            site = header.get("site") or site
            try:
                text = payload.decode("utf-8")
            except UnicodeDecodeError as e:
                audits.append(analysis.ProgramAudit(
                    site=site, parse_error=f"non-utf8 payload: {e}"))
                continue
            one(site, text)
    else:
        with open(target, "r", encoding="utf-8") as f:
            one(os.path.basename(target), f.read())
    return audits, alias_skipped


# ---------------------------------------------------------------------------
# selftest stages
# ---------------------------------------------------------------------------

def _stage_rules_known_answer(analysis) -> dict:
    per_rule = {}
    ok = True
    for rid, fx in sorted(analysis.FIXTURES.items()):
        bad = analysis.audit_module(fx["bad"], **fx["kwargs"])
        clean = analysis.audit_module(fx["clean"], **fx["kwargs"])
        nbad = sum(1 for v in bad if v.rule == rid)
        entry = {"bad": nbad, "bad_total": len(bad),
                 "clean": len(clean)}
        entry["ok"] = (nbad == 1 and len(bad) == 1 and not clean)
        ok = ok and entry["ok"]
        per_rule[rid] = entry
    return {"ok": ok, "per_rule": per_rule}


def _stage_pr18_gather(analysis) -> dict:
    """The PR 18 bug class, reproduced live: a with_sharding_constraint
    that pins a large tensor replicated on a multi-device mesh must be
    caught as MX015 by the static audit of the real lowered text; the
    sharded twin must be clean."""
    import jax
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devs = jax.devices()[:2]
    mesh = Mesh(np.asarray(devs), ("dp",))
    sharded = NamedSharding(mesh, P("dp"))
    repl = NamedSharding(mesh, P())
    x = jax.device_put(np.zeros((1024, 64), np.float32), sharded)

    def pinned_gather(v):
        return jax.lax.with_sharding_constraint(v * 2.0, repl)

    def pinned_sharded(v):
        return jax.lax.with_sharding_constraint(v * 2.0, sharded)

    bad_text = jax.jit(pinned_gather).lower(x).as_text()
    clean_text = jax.jit(pinned_sharded).lower(x).as_text()
    bad = analysis.audit_module(bad_text, site="pr18_gather_bad",
                                repl_bytes=1024)
    clean = analysis.audit_module(clean_text, site="pr18_gather_clean",
                                  repl_bytes=1024)
    bad_n = sum(1 for v in bad if v.rule == "MX015")
    clean_n = sum(1 for v in clean if v.rule == "MX015")
    return {"ok": bad_n >= 1 and clean_n == 0,
            "bad_mx015": bad_n, "clean_mx015": clean_n}


def _build_spmd_trainer(mx, shapes, spmd=True, fuse=False):
    import numpy as np
    from mxnet_tpu.gluon.parameter import Parameter
    from mxnet_tpu.gluon.trainer import Trainer
    from mxnet_tpu.ndarray.ndarray import array as nd_array

    ctx = [mx.cpu(0), mx.cpu(1)]
    rng = np.random.RandomState(0)
    params = []
    for i, shp in enumerate(shapes):
        p = Parameter(f"w{i}", shape=shp, dtype="float32")
        p.initialize(ctx=ctx)
        p.set_data(nd_array(rng.randn(*shp).astype("float32")))
        params.append(p)
    kw = {"fuse_step": True} if fuse else {"kvstore": "device",
                                           "spmd": True}
    t = Trainer(params, "sgd", {"momentum": 0.9}, **kw)

    def set_grads(step):
        r = np.random.RandomState(1000 + step)
        for p in params:
            g = r.randn(*p.shape).astype("float32")
            for rr, gnd in enumerate(p.list_grad()):
                gnd._data = nd_array(g * (rr + 1), ctx=gnd.ctx).data

    return t, set_grads


def _stage_live_and_wire(analysis) -> tuple:
    """Drive real fused + SPMD int8-quant compiles under
    MXNET_IR_AUDIT=1; the audits must be clean, and the SPMD program's
    static int8 wire lane must agree with the measured counter."""
    import mxnet_tpu as mx
    from mxnet_tpu.compile_cache import audit as _audit
    from mxnet_tpu.telemetry import instruments as _ins, tracing
    from mxnet_tpu.util import env as _env

    shapes = [(16, 8), (33,), (4, 3, 2)]
    _audit.reset()

    # fused single-replica-group step
    tf_, gf = _build_spmd_trainer(mx, shapes, fuse=True)
    gf(0)
    tf_.step(2)

    # SPMD int8-quant step (env set in main before the jax import)
    ts, gs = _build_spmd_trainer(mx, shapes, spmd=True)
    gs(0)
    ts.step(2)  # untraced warmup engages the mesh + compiles

    ops = ("reduce-scatter", "all-gather", "all-to-all", "all-reduce")
    tracing.enable()
    try:
        before = {op: _ins.collective_wire_bytes_total(
            op, "dp", "int8").value for op in ops}
        gs(1)
        ts.step(2)
        measured = sum(
            _ins.collective_wire_bytes_total(op, "dp", "int8").value
            - before[op] for op in ops)
    finally:
        tracing.disable()

    audits = _audit.audits()
    sites = {a.site: a for a in audits}
    nviol = sum(len(a.violations) for a in audits)
    nskip = sum(1 for a in audits if a.parse_skipped)
    live = {
        "ok": (nviol == 0 and nskip == 0
               and "optimizer.fused_step" in sites
               and "optimizer.spmd_step" in sites),
        "programs": sorted(sites),
        "violations": nviol,
        "parse_skipped": nskip,
    }

    spmd = sites.get("optimizer.spmd_step")
    static_int8 = 0
    if spmd is not None and spmd.wire:
        static_int8 = int(spmd.wire["by_lane"].get("int8", 0))
    tol = float(_env.get_float("MXNET_IR_WIRE_TOL") or 0.25)
    drift_msg = analysis.wire_drift(static_int8, measured, tol)
    drift = (abs(static_int8 - measured) / max(measured, 1.0))
    wire = {
        "ok": drift_msg is None and static_int8 > 0 and measured > 0,
        "static_int8_bytes": static_int8,
        "measured_int8_bytes": int(measured),
        "drift": round(drift, 4),
        "tol": tol,
        **({"message": drift_msg} if drift_msg else {}),
    }
    return live, wire, tf_, gf


def _stage_overhead(tf_, gf) -> dict:
    """The audit-off cost at a hooked compile site is one enabled()
    check; gate it at <= 3% of a fused optimizer step (the same
    tolerance the profiler overhead tests use)."""
    import gc

    from mxnet_tpu.compile_cache import audit as _audit

    os.environ.pop("MXNET_IR_AUDIT", None)
    assert not _audit.enabled()

    def best(fn, reps=5):
        out = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            fn()
            out = min(out, time.perf_counter() - t0)
        return out

    n_guard = 1000
    gc.disable()
    try:
        t_guard = best(lambda: [
            _audit.maybe_audit("overhead.probe", lambda: "")
            for _ in range(n_guard)]) / n_guard

        gf(2)

        def one_step():
            tf_.step(2)
        t_step = best(one_step)
    finally:
        gc.enable()
    ratio = t_guard / max(t_step, 1e-9)
    return {"ok": ratio <= 0.03, "guard_s": t_guard,
            "step_s": t_step, "ratio": round(ratio, 6)}


def _selftest(out_path: str | None) -> int:
    # env must be pinned BEFORE jax/mxnet_tpu import: 8 host devices
    # for the 2-device mesh, int8 comm-quant for the wire-model stage,
    # and the audit itself
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    os.environ["MXNET_IR_AUDIT"] = "1"
    os.environ["MXNET_COMM_QUANT"] = "int8"
    os.environ["MXNET_COMM_QUANT_MIN_SIZE"] = "1"
    os.environ["MXNET_ZERO_MIN_SIZE"] = "1"
    sys.path.insert(0, _REPO)

    analysis = _load_analysis()

    stages = {}
    stages["rules_known_answer"] = _stage_rules_known_answer(analysis)
    stages["pr18_gather"] = _stage_pr18_gather(analysis)
    live, wire, tf_, gf = _stage_live_and_wire(analysis)
    stages["live_audit"] = live
    stages["wire_model"] = wire
    stages["overhead"] = _stage_overhead(tf_, gf)

    from mxnet_tpu.compile_cache import audit as _audit
    gate_ok = all(s["ok"] for s in stages.values())
    doc = {
        "gate_ok": gate_ok,
        "stages": stages,
        "report": _audit.last_report(),
    }
    text = json.dumps(doc, indent=1, sort_keys=True) + "\n"
    if out_path:
        with open(out_path, "w", encoding="utf-8") as f:
            f.write(text)
    for name, s in stages.items():
        detail = json.dumps(
            {k: v for k, v in s.items() if k != "ok"},
            sort_keys=True)[:200]
        print(f"{'PASS' if s['ok'] else 'FAIL'}  {name}  {detail}")
    print(f"mxir --selftest: {'OK' if gate_ok else 'FAIL'}")
    return 0 if gate_ok else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="mxir", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("target", nargs="?", default=None,
                    help="compile-cache directory (*.mxcc) or a "
                         "StableHLO module text file")
    ap.add_argument("--json", action="store_true",
                    help="print the MXIR.json document to stdout")
    ap.add_argument("--out", default=None, metavar="FILE",
                    help="write the MXIR.json document to FILE")
    ap.add_argument("--repl-bytes", type=int, default=64 << 20,
                    help="MX015 threshold in bytes (default 64 MiB)")
    ap.add_argument("--selftest", action="store_true",
                    help="run the known-answer + live gate "
                         "(imports jax; drives real compiles)")
    args = ap.parse_args(argv)

    if args.selftest:
        return _selftest(args.out)

    if not args.target:
        ap.error("a cache directory / module file is required "
                 "(or --selftest)")
    if not os.path.exists(args.target):
        print(f"mxir: no such path: {args.target}", file=sys.stderr)
        return 2

    analysis = _load_analysis()
    audits, alias_skipped = _audit_offline(analysis, args.target,
                                           args.repl_bytes)
    doc = analysis.render_ir_json(audits, alias_skipped=alias_skipped)
    text = json.dumps(doc, indent=1, sort_keys=True) + "\n"
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(text)
    if args.json:
        sys.stdout.write(text)
    else:
        for a in audits:
            mark = "SKIP" if a.parse_skipped else (
                "FAIL" if a.violations else "ok")
            print(f"{mark:>4}  {a.site}  "
                  f"({len(a.violations)} finding(s))")
            for v in a.violations:
                print(f"      {v.rule} L{v.line}: {v.message}")
        c = doc["counts"]
        print(f"mxir: {c['programs']} program(s), "
              f"{c['violations']} violation(s), "
              f"{c['parse_skipped']} parse-skipped, "
              f"{c['alias_skipped']} alias-skipped")
    return 1 if doc["counts"]["violations"] else 0


if __name__ == "__main__":
    sys.exit(main())
