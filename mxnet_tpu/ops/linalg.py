"""Linear-algebra operators — the full linalg_* family.

TPU-native counterpart of the reference's src/operator/tensor/la_op.cc
(linalg_gemm/gemm2/potrf/potri/trmm/trsm/sumlogdiag/extractdiag/makediag/
extracttrian/maketrian/syrk/gelqf/syevd/inverse/det/slogdet).  Everything
lowers to XLA's native decompositions (cholesky/qr/eigh/triangular-solve
run as XLA HLO custom-calls on TPU) and inherits jax's gradients; batch
dimensions broadcast as in the reference (ops act on the last two axes).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .registry import register_op

__all__ = []


def _T(a):
    return jnp.swapaxes(a, -1, -2)


@register_op("linalg_gemm")
def _linalg_gemm(a, b, c, transpose_a=False, transpose_b=False, alpha=1.0,
                 beta=1.0, axis=-2):
    """alpha * op(A) @ op(B) + beta * C (ref: la_op.cc linalg_gemm).
    ``axis`` selects the matrix-ROW axis within ND inputs (default -2,
    the reference convention; other values move that axis into matrix
    position and back)."""
    move = axis not in (-2, a.ndim - 2)
    if move:
        a = jnp.moveaxis(a, axis, -2)
        b = jnp.moveaxis(b, axis, -2)
        c = jnp.moveaxis(c, axis, -2)
    if transpose_a:
        a = _T(a)
    if transpose_b:
        b = _T(b)
    out = alpha * jnp.matmul(a, b) + beta * c
    return jnp.moveaxis(out, -2, axis) if move else out


@register_op("linalg_potri")
def _linalg_potri(a):
    """Inverse of a PD matrix from its Cholesky factor L (A = L L^T):
    potri(L) = A^{-1} = L^{-T} L^{-1} (ref: la_op.cc linalg_potri)."""
    eye = jnp.broadcast_to(jnp.eye(a.shape[-1], dtype=a.dtype), a.shape)
    linv = jax.scipy.linalg.solve_triangular(a, eye, lower=True)
    return jnp.matmul(_T(linv), linv)


@register_op("linalg_trmm")
def _linalg_trmm(a, b, transpose=False, rightside=False, lower=True,
                 alpha=1.0):
    """Triangular matrix multiply: out = alpha * op(tri(A)) @ B
    (or B @ op(tri(A)) when rightside)."""
    tri = jnp.tril(a) if lower else jnp.triu(a)
    if transpose:
        tri = _T(tri)
    out = jnp.matmul(b, tri) if rightside else jnp.matmul(tri, b)
    return alpha * out


@register_op("linalg_trsm")
def _linalg_trsm(a, b, transpose=False, rightside=False, lower=True,
                 alpha=1.0):
    """Triangular solve: out = alpha * op(tri(A))^{-1} B
    (or alpha * B op(tri(A))^{-1} when rightside)."""
    solve = jax.scipy.linalg.solve_triangular
    if rightside:
        # X op(A) = B  <=>  op(A)^T X^T = B^T ; op(A)^T is A^T when not
        # transposed (trans=1) and A itself when transposed (trans=0)
        x = _T(solve(a, _T(b), lower=lower, trans=0 if transpose else 1))
    else:
        x = solve(a, b, lower=lower, trans=1 if transpose else 0)
    return alpha * x


@register_op("linalg_sumlogdiag")
def _linalg_sumlogdiag(a):
    """Sum of log of the diagonal of each [..., n, n] matrix (the
    log-det of a Cholesky factor)."""
    d = jnp.diagonal(a, axis1=-2, axis2=-1)
    return jnp.sum(jnp.log(d), axis=-1)


@register_op("linalg_extractdiag")
def _linalg_extractdiag(a, offset=0):
    """Extract the k-th diagonal of each [..., n, n] matrix as a vector."""
    return jnp.diagonal(a, offset=offset, axis1=-2, axis2=-1)


@register_op("linalg_makediag")
def _linalg_makediag(a, offset=0):
    """Embed a vector as the k-th diagonal of an otherwise-zero square
    matrix (inverse of ``linalg_extractdiag``)."""
    n = a.shape[-1] + abs(offset)
    base = jnp.zeros(a.shape[:-1] + (n, n), a.dtype)
    idx = jnp.arange(a.shape[-1])
    r = idx + max(0, -offset)
    c = idx + max(0, offset)
    return base.at[..., r, c].set(a)


def _trian_indices(n, offset, lower):
    if lower:
        r, c = np.tril_indices(n, k=offset)
    else:
        r, c = np.triu_indices(n, k=offset)
    return r, c


@register_op("linalg_extracttrian")
def _linalg_extracttrian(a, offset=0, lower=True):
    """Pack the lower (or upper) triangle of each [..., n, n] matrix
    into a flat row-major vector."""
    r, c = _trian_indices(a.shape[-1], offset, lower)
    return a[..., r, c]


@register_op("linalg_maketrian")
def _linalg_maketrian(a, offset=0, lower=True):
    """Unpack a flat triangle vector into an otherwise-zero square
    matrix (inverse of ``linalg_extracttrian``; n inferred from length)."""
    # solve k = n(n+1)/2 - |offset| terms for n given the packed length
    k = a.shape[-1]
    n = 1
    while True:
        r, c = _trian_indices(n, offset, lower)
        if len(r) == k:
            break
        n += 1
        if n > 4096:
            raise ValueError(f"cannot infer matrix size from {k} elements")
    base = jnp.zeros(a.shape[:-1] + (n, n), a.dtype)
    return base.at[..., r, c].set(a)


@register_op("linalg_syevd", num_outputs=2)
def _linalg_syevd(a):
    """Symmetric eigendecomposition: A = U^T diag(L) U with eigenvectors
    as ROWS of U (the reference's convention; jnp.linalg.eigh returns
    columns)."""
    w, v = jnp.linalg.eigh(a)
    return _T(v), w


@register_op("linalg_gelqf", num_outputs=2)
def _linalg_gelqf(a):
    """LQ factorization of a full-rank m x n (m <= n): A = L Q with Q's
    rows orthonormal (ref: la_op.cc linalg_gelqf).  Via QR of A^T."""
    q, r = jnp.linalg.qr(_T(a))
    return _T(r), _T(q)


@register_op("linalg_inverse", aliases=("inverse",))
def _linalg_inverse(a):
    """Matrix inverse of each [..., n, n] matrix."""
    return jnp.linalg.inv(a)


@register_op("linalg_det", aliases=("det",))
def _linalg_det(a):
    """Determinant of each [..., n, n] matrix."""
    return jnp.linalg.det(a)


@register_op("linalg_slogdet", aliases=("slogdet",), num_outputs=2)
def _linalg_slogdet(a):
    """Sign and log|det| of each [..., n, n] matrix (numerically safe
    where ``det`` would over/underflow)."""
    sign, logdet = jnp.linalg.slogdet(a)
    return sign, logdet


@register_op("linalg_solve", aliases=("solve",))
def _linalg_solve(a, b):
    """Solve the linear system A X = B for X (batched)."""
    return jnp.linalg.solve(a, b)


@register_op("moments", num_outputs=2)
def _moments(data, axes=None, keepdims=False):
    """Mean and variance over ``axes`` in one pass (ref: moments.cc)."""
    mean = jnp.mean(data, axis=axes, keepdims=keepdims)
    var = jnp.var(data, axis=axes, keepdims=keepdims)
    return mean, var
