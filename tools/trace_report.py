#!/usr/bin/env python
"""Summarize a chrome-trace dump into a per-phase step-time table.

The profiler + telemetry layer dumps one flat chrome://tracing JSON
(`mx.profiler.dump(...)`).  This CLI turns it into the table a BENCH
run attributes regressions with: per phase (data-wait, forward,
backward, grad-allreduce, optimizer-update; admission, queue-wait,
batch-assembly, execute, respond; per-op dispatch lanes), the count,
total/mean/min/max milliseconds, and share of trace wall time.

    python tools/trace_report.py trace.json            # table
    python tools/trace_report.py trace.json --json     # machine-readable
    python tools/trace_report.py trace.json --check    # integrity gate
    python tools/trace_report.py --selftest            # generate+check
    python tools/trace_report.py --merge r0.json r1.json \
        [--out merged.json]                            # multi-rank

`--json` carries the integrity verdict alongside the per-phase rows,
so harness consumers (scaling_bench --phases) read ONE machine
format instead of re-parsing the table.

`--merge` folds per-rank dumps (one profiler dump per process of an
SPMD job) into a single trace: per-rank clocks are aligned on the
collective spans — a blocking collective completes (nearly)
simultaneously on every rank, so matching occurrences pin the offset
— events are shifted onto rank 0's clock and re-homed to pid=rank,
and a cross-rank per-phase table with straggler/skew columns is
printed.  The merged trace passes `--check`.

`--check` validates trace integrity (the nightly lane runs it via
`--selftest`): the JSON parses, every event carries name/ph/ts/pid,
duration events carry dur, counter lanes that are cumulative counters
are monotone, flow arrows reference span trace ids that exist, and
span parent links resolve within their trace.  Exit 0 = clean,
1 = violations (printed), 2 = usage/IO error.

NOTE: --check expects a COMPLETE capture — dump at a quiescent point
(no requests in flight).  A periodic `dump(finished=True)` that cuts
a request mid-flight legitimately splits its flow/parent links across
two dumps; check the concatenation, not the pieces.
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import Optional
from collections import defaultdict

# counter-lane suffixes that are cumulative (monotone non-decreasing);
# point-in-time lanes (queue_depth, occupancy, ...) are exempt
MONOTONE_SUFFIXES = (
    "requests", "completed", "failed", "rejected", "deadline_expired",
    "batches", "batched_rows", "padded_rows", "cache_hits",
    "cache_misses", "_total",
)


def load_trace(path: str) -> list:
    with open(path) as f:
        data = json.load(f)
    if isinstance(data, list):  # chrome also accepts the bare array form
        return data
    events = data.get("traceEvents")
    if not isinstance(events, list):
        raise ValueError(f"{path}: no traceEvents array")
    return events


# ---------------------------------------------------------------------------
# report
# ---------------------------------------------------------------------------

def phase_rows(events: list) -> list:
    """[(cat, name, count, total_ms, mean_ms, min_ms, max_ms, share)]
    over the X (complete) events, sorted by total time desc."""
    groups: dict = defaultdict(list)
    lo, hi = None, None
    for ev in events:
        if ev.get("ph") != "X":
            continue
        ts, dur = ev.get("ts", 0.0), ev.get("dur", 0.0)
        lo = ts if lo is None else min(lo, ts)
        hi = ts + dur if hi is None else max(hi, ts + dur)
        groups[(ev.get("cat", ""), ev.get("name", ""))].append(dur)
    wall_us = (hi - lo) if (lo is not None and hi is not None and
                            hi > lo) else None
    rows = []
    for (cat, name), durs in groups.items():
        tot = sum(durs)
        rows.append((cat, name, len(durs), tot / 1e3,
                     tot / len(durs) / 1e3, min(durs) / 1e3,
                     max(durs) / 1e3,
                     (tot / wall_us) if wall_us else None))
    rows.sort(key=lambda r: -r[3])
    return rows


def render_table(events: list) -> str:
    rows = phase_rows(events)
    steps = sum(1 for ev in events
                if ev.get("ph") == "X" and ev.get("name") == "step")
    traces = {ev["args"]["trace_id"] for ev in events
              if ev.get("ph") == "X"
              and isinstance(ev.get("args"), dict)
              and "trace_id" in ev["args"]}
    out = [f"{'Category':<12s} {'Phase':<28s} {'Count':>7s} "
           f"{'Total(ms)':>11s} {'Mean(ms)':>10s} {'Min(ms)':>9s} "
           f"{'Max(ms)':>9s} {'%Wall':>7s}"]
    out.append("-" * len(out[0]))
    for cat, name, n, tot, mean, mn, mx, share in rows:
        pct = f"{share * 100:6.1f}%" if share is not None else "      -"
        out.append(f"{cat:<12.12s} {name:<28.28s} {n:>7d} {tot:>11.3f} "
                   f"{mean:>10.4f} {mn:>9.4f} {mx:>9.4f} {pct:>7s}")
    if not rows:
        out.append("(no duration events)")
    tail = [f"events: {len(events)}"]
    if steps:
        tail.append(f"training steps: {steps}")
    if traces:
        tail.append(f"distinct trace ids: {len(traces)}")
    out.append("  ".join(tail))
    return "\n".join(out)


def report_json(events: list) -> dict:
    """Machine-readable summary: per-phase rows + the integrity
    verdict (what `--check` would have said) in one document."""
    errs = check_events(events)
    return {
        "phases": [
            {"cat": cat, "name": name, "count": n,
             "total_ms": round(tot, 3), "mean_ms": round(mean, 4),
             "min_ms": round(mn, 4), "max_ms": round(mx, 4),
             "wall_share": None if share is None else round(share, 4)}
            for cat, name, n, tot, mean, mn, mx, share
            in phase_rows(events)],
        "num_events": len(events),
        "check": {"ok": not errs, "violations": errs},
    }


# ---------------------------------------------------------------------------
# integrity check
# ---------------------------------------------------------------------------

def check_events(events: list) -> list:
    """Returns a list of violation strings (empty = clean)."""
    errs = []
    span_ids_by_trace = defaultdict(set)
    trace_ids = set()
    counters = defaultdict(list)  # lane name -> [(ts, value)]
    for i, ev in enumerate(events):
        ph = ev.get("ph")
        for field in ("name", "ph", "ts", "pid"):
            if field not in ev:
                errs.append(f"event[{i}] missing {field!r}: {ev!r:.120}")
                break
        if ph == "X" and "dur" not in ev:
            errs.append(f"event[{i}] ({ev.get('name')!r}): X event "
                        f"without dur")
        args = ev.get("args")
        if ph == "X" and isinstance(args, dict) and "trace_id" in args:
            trace_ids.add(args["trace_id"])
            if "span_id" in args:
                span_ids_by_trace[args["trace_id"]].add(args["span_id"])
        if ph == "C" and isinstance(args, dict):
            for lane, v in args.items():
                if isinstance(v, (int, float)):
                    # keyed per process: in a merged multi-rank trace
                    # each rank keeps its OWN cumulative lanes, and
                    # clock-shifted cross-rank interleaving must not
                    # read as a decrease
                    counters[(ev.get("pid"), lane)].append(
                        (ev.get("ts", 0.0), v))
    # counter lanes expected monotone
    for (pid, lane), samples in counters.items():
        if not lane.endswith(MONOTONE_SUFFIXES):
            continue
        samples.sort(key=lambda sv: sv[0])
        last = None
        for ts, v in samples:
            if last is not None and v < last:
                errs.append(f"counter lane {lane!r} (pid {pid}) "
                            f"decreases ({last} -> {v}) but is "
                            f"cumulative")
                break
            last = v
    # flow arrows must reference a span's trace id
    for i, ev in enumerate(events):
        if ev.get("ph") in ("s", "f"):
            fid = ev.get("id")
            if fid not in trace_ids:
                errs.append(f"flow event[{i}] id {fid!r} references no "
                            f"span trace_id in this dump")
    # parent links resolve within their trace
    for i, ev in enumerate(events):
        args = ev.get("args")
        if ev.get("ph") != "X" or not isinstance(args, dict):
            continue
        parent = args.get("parent_id")
        if parent is None:
            continue
        tid = args.get("trace_id")
        if parent not in span_ids_by_trace.get(tid, ()):
            errs.append(f"event[{i}] ({ev.get('name')!r}) parent_id "
                        f"{parent!r} not found in trace {tid!r}")
    return errs


# ---------------------------------------------------------------------------
# multi-rank merge: clock-align per-rank dumps on their collective spans
# ---------------------------------------------------------------------------

def _rank_of(events: list, default: int) -> int:
    """The rank a dump came from: args.rank stamped by dist.init (via
    telemetry.tracing.set_rank), else the caller's file order."""
    for ev in events:
        args = ev.get("args")
        if isinstance(args, dict) and "rank" in args:
            try:
                return int(args["rank"])
            except (TypeError, ValueError):
                break
    return default


def _sync_marks(events: list) -> dict:
    """{(name, k): end_ts_us} for the k-th occurrence of each blocking
    sync span — collectives, plus the in-graph SPMD phases that embed
    a collective barrier (reduce-scatter / all-gather / spmd-step).
    A blocking collective completes near-simultaneously on every rank,
    so matched occurrences pin the per-rank clock offset."""
    seen = defaultdict(int)
    marks = {}
    evs = [ev for ev in events if ev.get("ph") == "X"]
    evs.sort(key=lambda ev: ev.get("ts", 0.0))
    for ev in evs:
        name, cat = ev.get("name"), ev.get("cat")
        if cat == "collective" or name in ("reduce-scatter",
                                           "all-gather", "spmd-step"):
            k = seen[name]
            seen[name] = k + 1
            marks[(name, k)] = ev.get("ts", 0.0) + ev.get("dur", 0.0)
    return marks


def merge_traces(per_rank: list) -> tuple:
    """[(rank, events), ...] -> (merged_events, info).

    Clock alignment: for every sync mark present on ALL ranks, the
    offset that maps rank r's end time onto rank 0's is averaged;
    events are shifted by it and re-homed to ``pid = rank`` so the
    merged trace shows one lane per rank.  info carries the applied
    offsets and the cross-rank skew table."""
    if not per_rank:
        return [], {"ranks": 0, "offsets_us": {}, "skew": []}
    ref_rank, ref_events = per_rank[0]
    ref_marks = _sync_marks(ref_events)
    offsets = {ref_rank: 0.0}
    aligned_on = {}
    for rank, events in per_rank[1:]:
        marks = _sync_marks(events)
        common = sorted(set(ref_marks) & set(marks))
        if common:
            offsets[rank] = sum(ref_marks[c] - marks[c]
                                for c in common) / len(common)
            aligned_on[rank] = len(common)
        else:
            offsets[rank] = 0.0  # nothing to align on: trust the clock
            aligned_on[rank] = 0
    merged = []
    totals = defaultdict(lambda: defaultdict(float))  # (cat,name)->rank->ms
    for rank, events in per_rank:
        off = offsets[rank]
        for ev in events:
            ev = dict(ev)
            if "ts" in ev:
                ev["ts"] = ev["ts"] + off
            ev["pid"] = rank
            if ev.get("ph") == "X":
                args = dict(ev.get("args") or {})
                args.setdefault("rank", rank)
                ev["args"] = args
                totals[(ev.get("cat", ""), ev.get("name", ""))][rank] \
                    += ev.get("dur", 0.0) / 1e3
            merged.append(ev)
    merged.sort(key=lambda ev: ev.get("ts", 0.0))
    ranks = [r for r, _ in per_rank]
    skew = []
    for (cat, name), per in sorted(totals.items(),
                                   key=lambda kv: -max(kv[1].values())):
        vals = {r: per.get(r, 0.0) for r in ranks}
        hi = max(vals, key=vals.get)
        lo = min(vals, key=vals.get)
        skew.append({
            "cat": cat, "name": name,
            "per_rank_ms": {str(r): round(v, 3)
                            for r, v in vals.items()},
            "skew_ms": round(vals[hi] - vals[lo], 3),
            "straggler": hi,
        })
    info = {"ranks": len(per_rank),
            "offsets_us": {str(r): round(o, 1)
                           for r, o in offsets.items()},
            "aligned_on_marks": {str(r): n
                                 for r, n in aligned_on.items()},
            "skew": skew}
    return merged, info


def merge_loaded(loaded: list, out: Optional[str] = None) -> tuple:
    """The one merge pipeline both the CLI --merge branch and
    scaling_bench's in-process merge run: rank detection (args.rank
    tags, falling back to input order on duplicates), clock-aligned
    merge, integrity check, and the optional merged-trace write.
    ``loaded`` is a list of event lists; returns (merged, info, errs).
    """
    per_rank = [(_rank_of(evs, i), evs)
                for i, evs in enumerate(loaded)]
    # duplicate rank tags (e.g. two single-process dumps) fall back to
    # input order so lanes never collide
    if len({r for r, _ in per_rank}) != len(per_rank):
        per_rank = [(i, evs) for i, evs in enumerate(loaded)]
    per_rank.sort(key=lambda re: re[0])
    merged, info = merge_traces(per_rank)
    errs = check_events(merged)
    if out:
        with open(out, "w") as f:
            json.dump({"traceEvents": merged,
                       "displayTimeUnit": "ms"}, f)
    return merged, info, errs


def render_rank_table(info: dict) -> str:
    ranks = sorted(int(r) for r in info["offsets_us"])
    hdr = (f"{'Category':<12s} {'Phase':<24s} "
           + " ".join(f"{'r%d(ms)' % r:>10s}" for r in ranks)
           + f" {'Skew(ms)':>9s} {'Straggler':>9s}")
    out = [hdr, "-" * len(hdr)]
    for row in info["skew"]:
        cells = " ".join(
            f"{row['per_rank_ms'].get(str(r), 0.0):>10.3f}"
            for r in ranks)
        out.append(f"{row['cat']:<12.12s} {row['name']:<24.24s} "
                   f"{cells} {row['skew_ms']:>9.3f} "
                   f"{'rank %d' % row['straggler']:>9s}")
    out.append("offsets(us): " + ", ".join(
        f"rank {r}: {info['offsets_us'][str(r)]:+.1f}" for r in ranks)
        + "  (aligned on " + ", ".join(
            f"{info['aligned_on_marks'].get(str(r), '-')}"
            for r in ranks if str(r) in info["aligned_on_marks"])
        + " sync marks)")
    return "\n".join(out)


# ---------------------------------------------------------------------------
# selftest: generate a real trace through the framework, then check it
# ---------------------------------------------------------------------------

def selftest(keep: bool = False) -> int:
    import os
    import tempfile

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, telemetry
    from mxnet_tpu.gluon import nn, Trainer
    from mxnet_tpu.gluon.data import ArrayDataset, DataLoader

    telemetry.enable()
    mx.profiler.start()
    try:
        net = nn.Dense(4, in_units=8)
        net.initialize()
        xs = np.random.RandomState(0).rand(12, 8).astype("float32")
        ys = np.random.RandomState(1).rand(12, 4).astype("float32")
        data = ArrayDataset(mx.nd.array(xs), mx.nd.array(ys))
        loader = DataLoader(data, batch_size=4)
        trainer = Trainer(net.collect_params(), "sgd",
                          {"learning_rate": 0.1})
        for x, y in loader:
            with autograd.record():
                loss = ((net(x) - y) ** 2).sum()
            loss.backward()
            trainer.step(4)
        mx.nd.waitall()
    finally:
        mx.profiler.stop()
        telemetry.disable()
    fd, path = tempfile.mkstemp(suffix=".json", prefix="mx_trace_")
    os.close(fd)
    mx.profiler.dump(finished=True, filename=path)
    events = load_trace(path)
    errs = check_events(events)
    print(render_table(events))
    names = {ev.get("name") for ev in events if ev.get("ph") == "X"}
    for phase in ("data-wait", "forward", "backward", "grad-allreduce",
                  "optimizer-update", "step"):
        if phase not in names:
            errs.append(f"selftest trace missing phase {phase!r}")
    for e in errs:
        print(f"CHECK FAIL: {e}", file=sys.stderr)
    if not keep:
        os.unlink(path)
    else:
        print(f"trace kept at {path}")
    print(f"selftest: {len(events)} events, "
          f"{'OK' if not errs else f'{len(errs)} violations'}")
    return 1 if errs else 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="per-phase summary + integrity check + multi-rank "
                    "merge for chrome-trace dumps")
    ap.add_argument("trace", nargs="*",
                    help="profiler.dump() JSON file(s); several only "
                         "with --merge")
    ap.add_argument("--check", action="store_true",
                    help="validate trace integrity instead of printing "
                         "the table")
    ap.add_argument("--json", action="store_true",
                    help="emit the summary as JSON (includes the "
                         "integrity verdict)")
    ap.add_argument("--merge", action="store_true",
                    help="clock-align + merge per-rank dumps; prints "
                         "the cross-rank skew table")
    ap.add_argument("--out", default=None,
                    help="with --merge: write the merged trace here")
    ap.add_argument("--selftest", action="store_true",
                    help="generate a trace via a tiny training loop, "
                         "then check it (nightly lane)")
    ap.add_argument("--keep", action="store_true",
                    help="with --selftest: keep the generated trace")
    args = ap.parse_args(argv)

    if args.selftest:
        return selftest(keep=args.keep)
    if not args.trace:
        ap.print_usage(sys.stderr)
        return 2
    try:
        loaded = [load_trace(t) for t in args.trace]
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    if args.merge:
        merged, info, errs = merge_loaded(loaded, out=args.out)
        if args.json:
            rep = report_json(merged)
            rep["merge"] = info
            print(json.dumps(rep, indent=1))
        else:
            print(render_rank_table(info))
            for e in errs:
                print(f"CHECK FAIL: {e}", file=sys.stderr)
            print(f"merged {len(loaded)} ranks, {len(merged)} events, "
                  f"{'OK' if not errs else f'{len(errs)} violations'}")
        return 1 if errs else 0

    if len(loaded) != 1:
        print("error: multiple traces require --merge", file=sys.stderr)
        return 2
    events = loaded[0]
    if args.check:
        errs = check_events(events)
        for e in errs:
            print(f"CHECK FAIL: {e}", file=sys.stderr)
        print(f"{args.trace[0]}: {len(events)} events, "
              f"{'OK' if not errs else f'{len(errs)} violations'}")
        return 1 if errs else 0
    if args.json:
        print(json.dumps(report_json(events), indent=1))
    else:
        print(render_table(events))
    return 0


if __name__ == "__main__":
    sys.exit(main())
