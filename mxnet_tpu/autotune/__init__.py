"""mxtune — goodput-optimal knob autotuning.

The subsystem that ACTS on the observability stack instead of adding to
it: it sweeps knob configurations through short measured runs (objective
= mxgoodput goodput ratio, tiebreak = mxprof MFU/throughput), persists
per-(scenario, mesh, device_kind, framework version) winners in a
content-addressed store beside the compile cache, and applies the best
stored config at import via an env-overlay that explicit ``MXNET_*``
settings always override.

Layout:

* :mod:`~mxnet_tpu.autotune.space` — search space derived from the knob
  registry's :class:`~mxnet_tpu.util.env.Tunable` metadata (declared
  where each knob is, never duplicated).
* :mod:`~mxnet_tpu.autotune.search` — successive halving with the
  default config pinned as an arm (tuned >= default by construction);
  crashed/timed-out trials are pruned, never fatal.
* :mod:`~mxnet_tpu.autotune.store` — verified, quarantining config
  store (compile-cache durability idiom).
* :mod:`~mxnet_tpu.autotune.startup` — boot-time overlay application.

Driver: ``tools/autotune.py`` (sweeps, ``--from-suspects`` feedback from
mxtriage, committed ``AUTOTUNE.json`` artifact).  Docs:
``docs/autotune.md``.
"""
from __future__ import annotations

from .search import successive_halving
from .space import (Dimension, dimensions, neighbor,
                    priority_from_suspects, sample)
from .startup import apply_startup_overlay
from .store import ConfigStore, config_fingerprint, default_dir, entry_key

__all__ = [
    "Dimension", "dimensions", "sample", "neighbor",
    "priority_from_suspects", "successive_halving",
    "ConfigStore", "config_fingerprint", "default_dir", "entry_key",
    "apply_startup_overlay",
]
