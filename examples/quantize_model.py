"""INT8 post-training quantization end to end
(ref: example/quantization/imagenet_gen_qsym.py + imagenet_inference.py).

Trains a small convnet on synthetic data via the symbolic Module path,
then calibrates + quantizes it with `contrib.quantization.quantize_model`
and compares fp32 vs int8 accuracy and latency.

Usage:
  python examples/quantize_model.py                 # TPU
  python examples/quantize_model.py --cpu --small   # CPU smoke (CI)
  python examples/quantize_model.py --calib-mode naive|entropy|none
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--small", action="store_true")
    ap.add_argument("--calib-mode", default="entropy",
                    choices=["none", "naive", "entropy"])
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--batch-size", type=int, default=64)
    args = ap.parse_args()

    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")

    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu.contrib.quantization import quantize_model

    np.random.seed(0)
    mx.random.seed(0)
    ctx = mx.cpu() if args.cpu else mx.tpu(0)
    size = 16 if args.small else 32
    nclass = 4 if args.small else 10
    if args.small:
        args.epochs, args.batch_size = 2, 32

    # ---- a learnable synthetic image task -------------------------------
    rng = np.random.RandomState(0)
    n = 512 if args.small else 4096

    def make_split(n):
        y = rng.randint(nclass, size=n)
        x = rng.randn(n, 3, size, size).astype("f4") * 0.3
        for i, cls in enumerate(y):  # class-dependent quadrant brightness
            qi, qj = divmod(cls % 4, 2)
            x[i, :, qi * size // 2:(qi + 1) * size // 2,
              qj * size // 2:(qj + 1) * size // 2] += 1.5 + 0.2 * cls
        return x, y.astype("f4")

    Xtr, ytr = make_split(n)
    Xte, yte = make_split(n // 4)

    # ---- symbolic model + Module.fit ------------------------------------
    data = mx.sym.var("data")
    net = mx.sym.Convolution(data, kernel=(3, 3), num_filter=16,
                             pad=(1, 1), name="conv1")
    net = mx.sym.Activation(net, act_type="relu", name="relu1")
    net = mx.sym.Pooling(net, kernel=(2, 2), stride=(2, 2),
                         pool_type="max", name="pool1")
    net = mx.sym.Convolution(net, kernel=(3, 3), num_filter=32,
                             pad=(1, 1), name="conv2")
    net = mx.sym.Activation(net, act_type="relu", name="relu2")
    net = mx.sym.Pooling(net, global_pool=True, kernel=(1, 1),
                         pool_type="avg", name="gap")
    net = mx.sym.FullyConnected(net, num_hidden=nclass, name="fc")
    net = mx.sym.SoftmaxOutput(net, mx.sym.var("softmax_label"),
                               name="softmax")

    train_iter = mx.io.NDArrayIter(Xtr, ytr, args.batch_size,
                                   shuffle=True, label_name="softmax_label")
    val_iter = mx.io.NDArrayIter(Xte, yte, args.batch_size,
                                 label_name="softmax_label")
    mod = mx.module.Module(net, context=ctx)
    mod.fit(train_iter, eval_data=val_iter, optimizer="adam",
            optimizer_params={"learning_rate": 3e-3},
            initializer=mx.initializer.Xavier(), num_epoch=args.epochs)
    arg_params, aux_params = mod.get_params()

    def accuracy(sym, params, aux):
        exe = None
        correct = total = 0
        t0 = None  # started AFTER the first batch: the cold forward is
        # XLA compile time, not inference latency
        val_iter.reset()
        for batch in val_iter:
            feed = dict(params, data=batch.data[0].as_in_context(ctx),
                        softmax_label=mx.nd.zeros(
                            (batch.data[0].shape[0],), ctx=ctx))
            if exe is None:
                exe = sym.bind(ctx, feed, grad_req="null",
                               aux_states=dict(aux))
            else:
                exe.copy_params_from({"data": batch.data[0]},
                                     allow_extra_params=True)
            out = exe.forward()[0].asnumpy()
            if t0 is None:
                t0 = time.time()  # clock starts once compiled
            pred = out.reshape(out.shape[0], -1).argmax(axis=1)
            lab = batch.label[0].asnumpy().astype(int)
            keep = out.shape[0] - batch.pad
            correct += (pred[:keep] == lab[:keep]).sum()
            total += keep
        return correct / total, time.time() - (t0 or time.time())

    fp32_acc, fp32_t = accuracy(net, arg_params, aux_params)
    print(f"fp32:  accuracy={fp32_acc:.4f}  ({fp32_t:.2f}s)")

    # ---- calibrate + quantize -------------------------------------------
    calib = [mx.nd.array(Xtr[i:i + args.batch_size], ctx=ctx)
             for i in range(0, 4 * args.batch_size, args.batch_size)]
    qsym, qargs, qaux = quantize_model(
        net, arg_params, aux_params, calib_mode=args.calib_mode,
        calib_data=None if args.calib_mode == "none" else calib,
        excluded_sym_names=("fc",))  # keep the tiny head fp32
    int8_acc, int8_t = accuracy(qsym, qargs, qaux)
    print(f"int8 ({args.calib_mode}): accuracy={int8_acc:.4f}  "
          f"({int8_t:.2f}s)")
    drop = fp32_acc - int8_acc
    print(f"accuracy drop: {drop:.4f}")
    if drop > 0.05:
        raise SystemExit("int8 accuracy dropped more than 5%")


if __name__ == "__main__":
    main()
