"""Evaluation metrics (ref: python/mxnet/metric.py): EvalMetric base +
registry/create, Accuracy, TopKAccuracy, F1, MCC, Perplexity, MAE, MSE,
RMSE, CrossEntropy, NegativeLogLikelihood, PearsonCorrelation, Loss,
CompositeEvalMetric, CustomMetric + np()."""
from __future__ import annotations

from typing import List, Optional, Sequence, Union

import numpy as _np

from .base import MXNetError, Registry

__all__ = ["EvalMetric", "create", "register", "Accuracy", "TopKAccuracy",
           "F1", "MCC", "Perplexity", "MAE", "MSE", "RMSE", "CrossEntropy",
           "NegativeLogLikelihood", "PearsonCorrelation", "Loss",
           "CompositeEvalMetric", "CustomMetric", "np"]

_REG: Registry = Registry("metric")
register = _REG.register


def create(metric, *args, **kwargs):
    if callable(metric):
        return CustomMetric(metric, *args, **kwargs)
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, list):
        composite = CompositeEvalMetric()
        for m in metric:
            composite.add(create(m, *args, **kwargs))
        return composite
    return _REG.get(metric)(*args, **kwargs)


def _to_numpy(x):
    if hasattr(x, "asnumpy"):
        return x.asnumpy()
    return _np.asarray(x)


class EvalMetric:
    def __init__(self, name, output_names=None, label_names=None, **kwargs):
        self.name = name
        self.output_names = output_names
        self.label_names = label_names
        self._kwargs = kwargs
        self.reset()

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0

    def update(self, labels, preds):
        raise NotImplementedError

    def update_dict(self, label: dict, pred: dict):
        if self.output_names is not None:
            pred = [pred[name] for name in self.output_names]
        else:
            pred = list(pred.values())
        if self.label_names is not None:
            label = [label[name] for name in self.label_names]
        else:
            label = list(label.values())
        self.update(label, pred)

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, self.sum_metric / self.num_inst)

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))

    def get_config(self):
        return {"metric": type(self).__name__, **self._kwargs}

    def __str__(self):
        return f"EvalMetric: {dict(self.get_name_value())}"


def _as_list(x):
    return x if isinstance(x, (list, tuple)) else [x]


@register("acc")
@register("accuracy")
class Accuracy(EvalMetric):
    def __init__(self, axis=1, name="accuracy", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names, axis=axis)
        self.axis = axis

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            pred = _to_numpy(pred)
            label = _to_numpy(label)
            if pred.ndim > label.ndim:
                pred = pred.argmax(axis=self.axis)
            pred = pred.astype("int32").flatten()
            label = label.astype("int32").flatten()
            if label.shape != pred.shape:
                raise MXNetError(
                    f"shape mismatch in Accuracy: {label.shape} vs {pred.shape}")
            self.sum_metric += (pred == label).sum()
            self.num_inst += len(label)


@register("top_k_accuracy")
@register("top_k_acc")
class TopKAccuracy(EvalMetric):
    def __init__(self, top_k=1, name="top_k_accuracy", output_names=None,
                 label_names=None):
        super().__init__(f"{name}_{top_k}", output_names, label_names,
                         top_k=top_k)
        self.top_k = top_k

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            pred = _to_numpy(pred)
            label = _to_numpy(label).astype("int32")
            topk = _np.argsort(pred, axis=-1)[:, -self.top_k:]
            for j in range(self.top_k):
                self.sum_metric += (topk[:, j].flatten() == label.flatten()).sum()
            self.num_inst += len(label)


@register("f1")
class F1(EvalMetric):
    def __init__(self, name="f1", output_names=None, label_names=None,
                 average="macro"):
        super().__init__(name, output_names, label_names, average=average)
        self.average = average
        self.reset_stats()

    def reset_stats(self):
        self._tp = self._fp = self._fn = 0

    def reset(self):
        super().reset()
        self.reset_stats()

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            pred = _to_numpy(pred)
            label = _to_numpy(label).astype("int32").flatten()
            if pred.ndim > 1 and pred.shape[-1] > 1:
                pred = pred.argmax(axis=-1)
            else:
                pred = (pred.flatten() > 0.5).astype("int32")
            pred = pred.astype("int32").flatten()
            self._tp += int(((pred == 1) & (label == 1)).sum())
            self._fp += int(((pred == 1) & (label == 0)).sum())
            self._fn += int(((pred == 0) & (label == 1)).sum())
            precision = self._tp / max(self._tp + self._fp, 1)
            recall = self._tp / max(self._tp + self._fn, 1)
            f1 = 2 * precision * recall / max(precision + recall, 1e-12)
            self.sum_metric = f1
            self.num_inst = 1


@register("mcc")
class MCC(EvalMetric):
    def __init__(self, name="mcc", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)
        self._tp = self._fp = self._tn = self._fn = 0

    def reset(self):
        super().reset()
        self._tp = self._fp = self._tn = self._fn = 0

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            pred = _to_numpy(pred)
            label = _to_numpy(label).astype("int32").flatten()
            if pred.ndim > 1 and pred.shape[-1] > 1:
                pred = pred.argmax(axis=-1)
            else:
                pred = (pred.flatten() > 0.5)
            pred = pred.astype("int32").flatten()
            self._tp += int(((pred == 1) & (label == 1)).sum())
            self._fp += int(((pred == 1) & (label == 0)).sum())
            self._tn += int(((pred == 0) & (label == 0)).sum())
            self._fn += int(((pred == 0) & (label == 1)).sum())
            num = self._tp * self._tn - self._fp * self._fn
            den = _np.sqrt(float((self._tp + self._fp) * (self._tp + self._fn)
                                * (self._tn + self._fp) * (self._tn + self._fn)))
            self.sum_metric = num / den if den > 0 else 0.0
            self.num_inst = 1


@register("perplexity")
class Perplexity(EvalMetric):
    def __init__(self, ignore_label=None, axis=-1, name="perplexity",
                 output_names=None, label_names=None):
        super().__init__(name, output_names, label_names,
                         ignore_label=ignore_label)
        self.ignore_label = ignore_label
        self.axis = axis

    def update(self, labels, preds):
        loss = 0.0
        num = 0
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            pred = _to_numpy(pred)
            label = _to_numpy(label).astype("int32").flatten()
            probs = pred.reshape(-1, pred.shape[-1])[_np.arange(len(label)), label]
            if self.ignore_label is not None:
                ignore = (label == self.ignore_label)
                probs = _np.where(ignore, 1.0, probs)
                num -= int(ignore.sum())
            loss -= _np.log(_np.maximum(probs, 1e-10)).sum()
            num += len(label)
        # accumulate total NLL and token count; exponentiate in get() so
        # multi-batch perplexity is exp(sum/count), not a mean of batch ppls
        self.sum_metric += float(loss)
        self.num_inst += num

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, float(_np.exp(self.sum_metric / self.num_inst)))


@register("mae")
class MAE(EvalMetric):
    def __init__(self, name="mae", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label = _to_numpy(label)
            pred = _to_numpy(pred)
            if label.ndim == 1:
                label = label.reshape(label.shape[0], 1)
            if pred.ndim == 1:
                pred = pred.reshape(pred.shape[0], 1)
            self.sum_metric += float(_np.abs(label - pred).mean())
            self.num_inst += 1


@register("mse")
class MSE(EvalMetric):
    def __init__(self, name="mse", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label = _to_numpy(label)
            pred = _to_numpy(pred)
            if label.ndim == 1:
                label = label.reshape(label.shape[0], 1)
            if pred.ndim == 1:
                pred = pred.reshape(pred.shape[0], 1)
            self.sum_metric += float(((label - pred) ** 2).mean())
            self.num_inst += 1


@register("rmse")
class RMSE(MSE):
    def __init__(self, name="rmse", output_names=None, label_names=None):
        EvalMetric.__init__(self, name, output_names, label_names)

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, float(_np.sqrt(self.sum_metric / self.num_inst)))


@register("ce")
@register("cross-entropy")
class CrossEntropy(EvalMetric):
    def __init__(self, eps=1e-12, name="cross-entropy", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names, eps=eps)
        self.eps = eps

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label = _to_numpy(label).ravel().astype("int32")
            pred = _to_numpy(pred)
            prob = pred[_np.arange(label.shape[0]), label]
            self.sum_metric += float((-_np.log(prob + self.eps)).sum())
            self.num_inst += label.shape[0]


@register("nll_loss")
class NegativeLogLikelihood(CrossEntropy):
    def __init__(self, eps=1e-12, name="nll-loss", output_names=None,
                 label_names=None):
        super().__init__(eps, name, output_names, label_names)


@register("pearsonr")
class PearsonCorrelation(EvalMetric):
    def __init__(self, name="pearsonr", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label = _to_numpy(label).ravel()
            pred = _to_numpy(pred).ravel()
            self.sum_metric += float(_np.corrcoef(pred, label)[0, 1])
            self.num_inst += 1


@register("loss")
class Loss(EvalMetric):
    def __init__(self, name="loss", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, _, preds):
        for pred in _as_list(preds):
            loss = float(_to_numpy(pred).sum())
            self.sum_metric += loss
            self.num_inst += int(_np.prod(_to_numpy(pred).shape))


class CompositeEvalMetric(EvalMetric):
    def __init__(self, metrics=None, name="composite", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names)
        self.metrics = [create(m) for m in (metrics or [])]

    def add(self, metric):
        self.metrics.append(create(metric))

    def get_metric(self, index):
        return self.metrics[index]

    def update(self, labels, preds):
        for m in self.metrics:
            m.update(labels, preds)

    def update_dict(self, labels, preds):
        for m in self.metrics:
            m.update_dict(labels, preds)

    def reset(self):
        for m in getattr(self, "metrics", []):
            m.reset()

    def get(self):
        names, values = [], []
        for m in self.metrics:
            n, v = m.get()
            names.extend(n if isinstance(n, list) else [n])
            values.extend(v if isinstance(v, list) else [v])
        return (names, values)


class CustomMetric(EvalMetric):
    def __init__(self, feval, name=None, allow_extra_outputs=False,
                 output_names=None, label_names=None):
        name = name or getattr(feval, "__name__", "custom")
        super().__init__(f"custom({name})", output_names, label_names)
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            reval = self._feval(_to_numpy(label), _to_numpy(pred))
            if isinstance(reval, tuple):
                sum_metric, num_inst = reval
                self.sum_metric += sum_metric
                self.num_inst += num_inst
            else:
                self.sum_metric += reval
                self.num_inst += 1


def np(numpy_feval, name=None, allow_extra_outputs=False):
    """Wrap a numpy feval into a CustomMetric (ref: metric.np)."""

    def feval(label, pred):
        return numpy_feval(label, pred)

    feval.__name__ = name or getattr(numpy_feval, "__name__", "custom")
    return CustomMetric(feval, name, allow_extra_outputs)
