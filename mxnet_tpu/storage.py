"""Storage / device-memory introspection and allocator knobs.

TPU-native counterpart of the reference's storage manager surface
(ref: src/storage/** pooled_storage_manager + MXNET_GPU_MEM_POOL_* env
knobs + mx.context.gpu_memory_info).  Allocation itself belongs to
PjRt/XLA by design (SURVEY.md N3: "delegate to PjRt, expose the
introspection"); this module exposes what a user needs when a model
OOMs:

  * memory_info(ctx)     -> (free_bytes, total_bytes) like the
    reference's gpu_memory_info, from the device's PjRt allocator stats.
  * memory_summary(ctx)  -> allocator stats + FRAMEWORK-side live-buffer
    accounting (count/bytes of live jax arrays per device) that works
    even on PJRT plugins that do not report allocator stats (this
    container's axon tunnel is one).
  * configure(...)       -> the reference's pool knobs mapped onto XLA's
    client options (must run before backend init, like the reference's
    env-var contract):
        pool_reserve_pct  <- MXNET_GPU_MEM_POOL_RESERVE
        preallocate       <- (XLA_PYTHON_CLIENT_PREALLOCATE)
"""
from __future__ import annotations

import os
from typing import Dict, Optional, Tuple

from .base import MXNetError
from .util import env

__all__ = ["memory_info", "memory_summary", "memory_summaries",
           "configure", "live_array_bytes"]


def _device_of(ctx=None):
    import jax

    from .context import Context, current_context

    ctx = ctx or current_context()
    if isinstance(ctx, Context):
        return ctx.jax_device
    return ctx  # already a jax device


def live_array_bytes(ctx=None) -> Tuple[int, int]:
    """(n_live_arrays, total_bytes) of framework-visible live buffers on
    the device — allocator-independent accounting."""
    import jax

    dev = _device_of(ctx)
    n = total = 0
    for a in jax.live_arrays():
        try:
            if dev in a.devices():
                n += 1
                total += a.nbytes // max(1, len(a.devices()))
        except Exception:  # deleted/donated buffers
            continue
    return n, total


def memory_summaries(devices=None) -> Dict[object, Tuple[int, int]]:
    """Live-buffer accounting for MANY devices in ONE pass over
    ``jax.live_arrays()`` -> {device: (n_live, total_bytes)}.  The
    per-device :func:`live_array_bytes` rescans the whole live set per
    call; telemetry's HBM sampling (mxprof) wants every local device
    at once, so this amortizes the scan."""
    import jax

    devs = list(devices) if devices is not None else jax.local_devices()
    acc: Dict[object, list] = {d: [0, 0] for d in devs}
    for a in jax.live_arrays():
        try:
            adevs = a.devices()
            share = a.nbytes // max(1, len(adevs))
            for d in adevs:
                slot = acc.get(d)
                if slot is not None:
                    slot[0] += 1
                    slot[1] += share
        except Exception:  # deleted/donated buffers
            continue
    return {d: (n, total) for d, (n, total) in acc.items()}


def memory_info(ctx=None) -> Tuple[int, int]:
    """(free_bytes, total_bytes) for the device
    (ref: mx.context.gpu_memory_info -> cudaMemGetInfo).  Raises
    MXNetError when the PJRT plugin does not report allocator stats —
    with the live-buffer fallback mentioned in the message."""
    dev = _device_of(ctx)
    stats = dev.memory_stats()
    if not stats:
        n, used = live_array_bytes(ctx)
        raise MXNetError(
            f"device {dev} does not report allocator stats "
            f"(PJRT plugin limitation); framework-side live buffers: "
            f"{n} arrays / {used} bytes — see storage.memory_summary")
    total = stats.get("bytes_limit") or stats.get("bytes_reservable_limit")
    in_use = stats.get("bytes_in_use", 0)
    if total is None:
        total = stats.get("peak_bytes_in_use", in_use)
    return int(total) - int(in_use), int(total)


def memory_summary(ctx=None) -> Dict[str, object]:
    """Full introspection dict: PjRt allocator stats (when available) +
    live-buffer accounting (always)."""
    dev = _device_of(ctx)
    try:
        stats = dev.memory_stats() or {}
    except Exception:
        stats = {}
    n, used = live_array_bytes(ctx)
    return {
        "device": str(dev),
        "platform": dev.platform,
        "allocator_stats": dict(stats),
        "live_arrays": n,
        "live_array_bytes": used,
    }


def configure(pool_reserve_pct: Optional[int] = None,
              preallocate: Optional[bool] = None) -> None:
    """Set allocator knobs (must run BEFORE the jax backend initializes,
    the same contract as the reference's MXNET_GPU_MEM_POOL_* env vars).

    pool_reserve_pct: percent of device memory to keep OUT of the pool
        (ref: MXNET_GPU_MEM_POOL_RESERVE) -> XLA client mem fraction.
    preallocate: grab the pool up front vs grow on demand.
    """
    import jax

    try:
        initialized = bool(jax._src.xla_bridge._backends)
    except Exception:
        initialized = False
    if initialized:
        raise MXNetError(
            "storage.configure must be called before the first jax "
            "backend use (same before-init contract as the reference's "
            "MXNET_GPU_MEM_POOL_* variables)")
    if pool_reserve_pct is not None:
        if not 0 <= pool_reserve_pct < 100:
            raise MXNetError("pool_reserve_pct must be in [0, 100)")
        os.environ["XLA_PYTHON_CLIENT_MEM_FRACTION"] = str(
            (100 - pool_reserve_pct) / 100.0)
    if preallocate is not None:
        os.environ["XLA_PYTHON_CLIENT_PREALLOCATE"] = \
            "true" if preallocate else "false"


def _env_pool_reserve_default() -> None:
    """Honor the reference env var spelling at import."""
    reserve = env.get_int("MXNET_GPU_MEM_POOL_RESERVE")
    if reserve is not None and \
            "XLA_PYTHON_CLIENT_MEM_FRACTION" not in os.environ:
        os.environ["XLA_PYTHON_CLIENT_MEM_FRACTION"] = str(
            (100 - reserve) / 100.0)


_env_pool_reserve_default()
