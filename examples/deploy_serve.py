"""Train, export to a portable StableHLO artifact, serve without the
model class — the TPU-native version of the reference's deploy flow
(ref: docs/faq/smart_device.md: save -symbol.json + .params, reload in
the C++ predictor).

    python examples/deploy_serve.py [--out DIR] [--dynamic-batch]

Step 1 trains a small MLP on synthetic data; step 2 `export_model`s it
(one directory: model.stablehlo + model.params + meta.json); step 3
reloads with `import_model` — note no _Net class in scope — and serves
a few batches, comparing against the live network.
"""
import argparse
import tempfile

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd
from mxnet_tpu.contrib import deploy
from mxnet_tpu.gluon import Trainer, loss as gloss, nn


def train(net, steps=30):
    rng = np.random.RandomState(0)
    X = rng.randn(256, 16).astype("float32")
    y = (X[:, 0] * X[:, 1] > 0).astype("int32")
    trainer = Trainer(net.collect_params(), "adam",
                      {"learning_rate": 1e-2})
    lfn = gloss.SoftmaxCrossEntropyLoss()
    for step in range(steps):
        with autograd.record():
            l = lfn(net(nd.array(X)), nd.array(y))
        l.backward()
        trainer.step(len(X))
    print(f"trained: final loss {float(l.mean().asnumpy()):.4f}")
    return net


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None,
                    help="artifact directory (default: a temp dir)")
    ap.add_argument("--dynamic-batch", action="store_true",
                    help="export with a free batch dimension")
    args = ap.parse_args()

    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(32, activation="relu", in_units=16))
        net.add(nn.Dense(2, in_units=32))
    net.initialize(mx.initializer.Xavier(), ctx=mx.cpu())
    net.hybridize()
    train(net)

    out = args.out or tempfile.mkdtemp(prefix="deploy_")
    example = nd.zeros((8, 16))
    deploy.export_model(net, out, [example],
                        dynamic_batch=args.dynamic_batch)
    print(f"exported -> {out}")

    served = deploy.import_model(out)   # no model code needed from here
    batches = (8,) if not args.dynamic_batch else (1, 8, 64)
    for n in batches:
        x = nd.array(np.random.RandomState(n).randn(n, 16)
                     .astype("float32"))
        got = served(x).asnumpy()
        ref = net(x).asnumpy()
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)
        print(f"served batch {n}: output {got.shape}, matches live net")
    print("deploy round-trip OK")


if __name__ == "__main__":
    main()
