"""Operator registry + imperative invoke path.

TPU-native counterpart of the reference's op machinery:
  - nnvm op registry with FCompute kernels (ref: src/operator/**,
    NNVM_REGISTER_OP, FCompute<xpu>)
  - Imperative::Invoke dispatch (ref: src/imperative/imperative.cc)
  - the dependency engine's async execution (ref: src/engine/threaded_engine.cc)

Design (idiomatic TPU, not a port):
  * Every op is a PURE jax function ``fn(*arrays, **attrs)``.  Shape/dtype
    inference is obtained from ``jax.eval_shape`` instead of hand-written
    FInferShape/FInferType.
  * The eager path compiles and caches one XLA executable per
    (op, attrs, input shapes/dtypes) via ``jax.jit`` — the counterpart of
    the reference's per-op CUDA kernel + engine push.  Dispatch is async
    (PjRt returns futures), so the Python thread does not block — the same
    contract the reference's ThreadedEngine provides.
  * Gradients come from ``jax.vjp`` on the same pure function, compiled and
    cached per signature at backward time.  XLA dead-code-eliminates the
    forward recomputation inside the vjp when it isn't needed, so this is
    cheap — and the true perf path is hybridize (one fused program).
"""
from __future__ import annotations

import functools
import itertools
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from ..analysis import sanitizer as _mxsan
from ..base import MXNetError, Registry
from ..util import env
from .. import profiler as _profiler
from ..telemetry import instruments as _tinstruments
from ..telemetry import metrics as _tmetrics
from ..telemetry import tracing as _tracing

__all__ = ["Operator", "register_op", "get_op", "list_ops", "invoke",
           "apply_pure", "dispatch"]


class Operator:
    """A registered op: pure jax fn + metadata.

    Parameters
    ----------
    name : canonical CamelCase or snake_case op name (reference-compatible).
    fn : pure function of positional jax arrays and keyword attrs.
    num_outputs : static output count, or a callable(attrs)->int.
    differentiable : if False, never recorded on the autograd tape.
    mutate_inputs : indices of inputs that the *frontend* treats as mutated
        (optimizer update ops); purely informational — the pure fn returns
        the new value and the frontend rebinds the NDArray buffer.
    """

    def __init__(self, name: str, fn: Callable, *, num_outputs=1,
                 differentiable: bool = True, mutate_inputs: Sequence[int] = (),
                 aliases: Sequence[str] = (), no_jit: bool = False):
        self.name = name
        self.fn = fn
        self.num_outputs = num_outputs
        self.differentiable = differentiable
        self.mutate_inputs = tuple(mutate_inputs)
        self.aliases = tuple(aliases)
        # eager-only op: output shape depends on input VALUES (boolean_mask)
        # — cannot be traced/jitted; invoke calls fn on concrete arrays
        self.no_jit = no_jit
        self._build_descriptor()

    # ---- typed attribute descriptor (the dmlc::Parameter role:
    # DMLC_DECLARE_PARAMETER declares name/type/default per op attr and
    # rejects unknown kwargs; here the descriptor is derived from the pure
    # fn's signature — parameters with defaults are attrs, the rest are
    # array inputs) -------------------------------------------------------
    def _build_descriptor(self):
        import inspect

        self.attr_defaults: Dict[str, Any] = {}
        self.input_names: List[str] = []
        self.allow_any_attr = False
        try:
            sig = inspect.signature(self.fn)
        except (TypeError, ValueError):
            self.allow_any_attr = True
            return
        self.param_order: List[str] = []
        self.param_default: Dict[str, Any] = {}
        for p in sig.parameters.values():
            if p.kind == inspect.Parameter.VAR_KEYWORD:
                self.allow_any_attr = True
            elif p.kind == inspect.Parameter.VAR_POSITIONAL:
                self.input_names.append("*" + p.name)
            elif p.default is inspect.Parameter.empty:
                self.input_names.append(p.name)
                self.param_order.append(p.name)
            else:
                self.attr_defaults[p.name] = p.default
                self.param_order.append(p.name)
                self.param_default[p.name] = p.default

    def validate_attrs(self, attrs: dict) -> dict:
        """Reject unknown attributes loudly and coerce reference-style
        string values ("(3, 3)", "64", "True") to the declared type.
        Returns the (possibly coerced) attrs dict."""
        if self.allow_any_attr:
            return attrs
        out = None
        for k, v in attrs.items():
            if k not in self.attr_defaults:
                if k.startswith("__"):  # scope attrs (__lr_mult__ etc)
                    continue
                raise MXNetError(
                    f"operator {self.name!r} has no attribute {k!r}; "
                    f"valid attributes: {sorted(self.attr_defaults)} "
                    f"(array inputs: {self.input_names})")
            d = self.attr_defaults[k]
            if isinstance(v, str) and d is not None \
                    and not isinstance(d, str):
                import ast

                try:
                    cv = ast.literal_eval(v)
                except (ValueError, SyntaxError):
                    raise MXNetError(
                        f"operator {self.name!r} attribute {k!r}: cannot "
                        f"parse {v!r} as {type(d).__name__}")
                if out is None:
                    out = dict(attrs)
                out[k] = cv
        return attrs if out is None else out

    @property
    def param_doc(self) -> str:
        """Generated parameter section (ref: dmlc Parameter __DOC__)."""
        lines = []
        if self.input_names:
            lines.append("Array inputs: " + ", ".join(self.input_names))
        if self.attr_defaults:
            lines.append("Attributes:")
            for k, d in self.attr_defaults.items():
                tname = type(d).__name__ if d is not None else "optional"
                lines.append(f"    {k} : {tname}, default {d!r}")
        if self.allow_any_attr:
            lines.append("(accepts free-form keyword attributes)")
        return "\n".join(lines)

    def nout(self, attrs: dict) -> int:
        if callable(self.num_outputs):
            return self.num_outputs(attrs)
        return self.num_outputs

    def __repr__(self):
        return f"Op({self.name})"


OP_REGISTRY: Registry[Operator] = Registry("operator", lowercase=False)


def register_op(name: str, *, num_outputs=1, differentiable: bool = True,
                mutate_inputs: Sequence[int] = (), aliases: Sequence[str] = (),
                no_jit: bool = False):
    """Decorator: register a pure jax function as a framework op."""

    def _wrap(fn: Callable) -> Callable:
        op = Operator(name, fn, num_outputs=num_outputs,
                      differentiable=differentiable,
                      mutate_inputs=mutate_inputs, aliases=aliases,
                      no_jit=no_jit)
        OP_REGISTRY.register(name)(op)
        for a in aliases:
            OP_REGISTRY.register(a)(op)
        return fn

    return _wrap


def get_op(name: str) -> Operator:
    return OP_REGISTRY.get(name)


def list_ops() -> List[str]:
    return OP_REGISTRY.list()


# --------------------------------------------------------------------------
# attrs normalisation — attrs must be hashable to key the executable cache
# (counterpart of dmlc::Parameter's typed, canonicalised op kwargs).
# --------------------------------------------------------------------------

def _freeze(v):
    if isinstance(v, (list, tuple)):
        return tuple(_freeze(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((k, _freeze(x)) for k, x in v.items()))
    if isinstance(v, np.ndarray):
        return ("__nparr__", v.shape, str(v.dtype), v.tobytes())
    if isinstance(v, np.generic):
        return v.item()
    return v


def freeze_attrs(attrs: dict) -> Tuple:
    return tuple(sorted((k, _freeze(v)) for k, v in attrs.items()))


def thaw_attrs(key: Tuple) -> dict:
    return {k: v for k, v in key}


# --------------------------------------------------------------------------
# Executable caches (counterpart: CachedOp-per-op + cuDNN autotune cache).
# jax.jit itself caches per input shape/dtype; we cache the jitted callable
# per (op, attrs) so attrs are baked in as static values.
# --------------------------------------------------------------------------

_jit_lock = threading.Lock()
# mxsan annotations: reads are the optimistic half of the
# double-checked idiom (deliberately lock-free); writes must stay
# under _jit_lock — the sanitizer verifies exactly that at runtime.
# Values are _CacheEntry cells (callable + LRU tick); both caches are
# BOUNDED by MXNET_OP_CACHE_MAX so attr-churning workloads (dynamic
# shapes through reshape/slice attrs) cannot grow them without bound.
_jit_cache: Dict[Tuple, "_CacheEntry"] = _mxsan.track(
    {}, "ops.registry._jit_cache", reads="unlocked-ok")
_grad_cache: Dict[Tuple, "_CacheEntry"] = _mxsan.track(
    {}, "ops.registry._grad_cache", reads="unlocked-ok")
_cache_ticks = itertools.count(1)
# all three counters mutate under _jit_lock (the _AotDispatch per-sig
# evictions re-acquire it after their instance lock just to count)
_cache_evictions = {"ops_jit": 0, "ops_grad": 0, "ops_aot": 0}

# MXNET_ENGINE_TYPE=NaiveEngine → fully synchronous execution for debugging
# (ref: src/engine/naive_engine.cc). Any other value = async (default).
_NAIVE = env.get_str("MXNET_ENGINE_TYPE") == "NaiveEngine"

# MXNET_COMPILE_CACHE_OPS=1 routes per-op executables through the
# persistent compile cache (AOT per input signature).  Read once, like
# _NAIVE; tests toggle via _refresh_ops_aot().
_OPS_AOT = env.get_bool("MXNET_COMPILE_CACHE_OPS")


def _refresh_ops_aot() -> bool:
    """Re-read the knob and drop cached callables built under the old
    mode (test hook; production reads the knob once at import)."""
    global _OPS_AOT
    _OPS_AOT = env.get_bool("MXNET_COMPILE_CACHE_OPS")
    with _jit_lock:
        _jit_cache.clear()
        _grad_cache.clear()
    return _OPS_AOT


class _CacheEntry:
    """Cached jit/grad callable.  ``tick`` is LRU recency, refreshed by
    a plain attribute write on the lock-free hit path; the eviction
    scan under _jit_lock reads it."""

    __slots__ = ("fn", "tick")

    def __init__(self, fn):
        self.fn = fn
        self.tick = next(_cache_ticks)


def _cache_hit(cache: Dict[Tuple, "_CacheEntry"], key: Tuple):
    e = cache.get(key)
    if e is None:
        return None
    e.tick = next(_cache_ticks)
    return e.fn


def _cache_insert_locked(cache: Dict[Tuple, "_CacheEntry"], key: Tuple,
                         fn: Callable, store: str) -> None:
    """Insert + bounded-LRU eviction.  Caller holds _jit_lock (both
    caches share it, matching the existing locking discipline)."""
    cache[key] = _CacheEntry(fn)
    cap = env.get_int("MXNET_OP_CACHE_MAX")
    evicted = 0
    while cap and len(cache) > cap:
        oldest = min(cache.items(), key=lambda kv: kv[1].tick)[0]
        if oldest == key:
            break  # never evict what we just inserted
        del cache[oldest]
        _cache_evictions[store] += 1  # mxlint: disable=MX004 — caller holds _jit_lock
        evicted += 1
    if evicted:
        _tinstruments.compile_cache_evict_total(store).inc(evicted)


def _first_party_fn(fn: Callable) -> bool:
    """Whether a registered op's implementation lives in this package
    (gates alias-key eligibility — see _AotDispatch and
    compile_cache.first_party, the one policy implementation)."""
    from ..compile_cache import first_party

    return first_party(getattr(fn, "__module__", ""))


def cache_info() -> Dict[str, int]:
    """Sizes + eviction counts of the in-process op executable caches
    (the bounded-cache tests assert on this).  ``aot_evictions``
    aggregates per-signature drops across every _AotDispatch wrapper
    (MXNET_COMPILE_CACHE_OPS=1)."""
    with _jit_lock:
        return {"jit_entries": len(_jit_cache),
                "grad_entries": len(_grad_cache),
                "jit_evictions": _cache_evictions["ops_jit"],
                "grad_evictions": _cache_evictions["ops_grad"],
                "aot_evictions": _cache_evictions["ops_aot"]}


class _AotDispatch:
    """Opt-in wrapper (MXNET_COMPILE_CACHE_OPS=1): dispatches through
    AOT-compiled executables obtained from the persistent compile
    cache, one per concrete input signature.  Falls back to the lazy
    ``jax.jit`` callable whenever an argument is not a committed
    concrete ``jax.Array`` (python scalars, numpy, tracers) — AOT needs
    exact avals, and correctness beats persistence.

    ``use_alias=False`` (user-registered ops, i.e. ``op.fn`` outside
    the ``mxnet_tpu`` namespace) disables the cheap alias index: an
    alias key cannot see the op's implementation, and unlike
    first-party code a user edit does not bump the framework version
    that invalidates the store — the full program-text key (built
    after lower) stays the only disk key, so a changed implementation
    can never be served a stale executable."""

    __slots__ = ("_site", "_lazy", "_ckey", "_per_sig", "_lock",
                 "_use_alias")

    def __init__(self, site: str, lazy: Callable, ckey: Tuple,
                 use_alias: bool = True):
        self._site = site
        self._lazy = lazy
        self._ckey = ckey
        self._per_sig: Dict[Tuple, "_CacheEntry"] = {}
        self._lock = threading.Lock()
        self._use_alias = use_alias

    def _sig(self, args) -> Optional[Tuple]:
        leaves = jax.tree_util.tree_leaves(args)
        parts = []
        for a in leaves:
            if not isinstance(a, jax.Array) or \
                    isinstance(a, jax.core.Tracer):
                return None
            parts.append((tuple(a.shape), str(a.dtype),
                          bool(a.weak_type),
                          tuple(sorted(str(d) for d in a.devices()))))
        return (jax.tree_util.tree_structure(args), tuple(parts))

    def __call__(self, *args):
        sig = self._sig(args)
        if sig is None:
            return self._lazy(*args)
        ent = self._per_sig.get(sig)  # GIL-atomic instance-dict read
        if ent is not None:
            ent.tick = next(_cache_ticks)
            return ent.fn(*args)
        evicted = 0
        with self._lock:
            ent = self._per_sig.get(sig)
            if ent is None:
                from .. import compile_cache as _cc

                cell = {}

                def lowered():
                    low = cell.get("lowered")
                    if low is None:
                        low = cell["lowered"] = \
                            self._lazy.lower(*args)
                    return low

                # alias: op identity + attrs + avals — no tracing; a
                # warm process dispatches its first op without
                # lowering it (first-party ops only, see class doc)
                alias = _cc.cache_key(
                    "ops.alias", parts=(self._ckey, sig)) \
                    if self._use_alias else None
                fn, origin = _cc.get_or_compile(
                    self._site,
                    lambda: _cc.cache_key(
                        "ops", parts=(self._ckey, sig),
                        program_text=lowered().as_text(),
                        components={"op": self._ckey, "avals": sig}),
                    lambda: lowered().compile(), alias=alias)
                _mxsan.record_compile(
                    self._site, (self._ckey, sig),
                    provenance="build" if origin == "compiled"
                    else "cache")
                ent = self._per_sig[sig] = _CacheEntry(fn)
                # same bound as the (op, attrs) caches: per-signature
                # executables must not grow without limit under
                # dynamic-shape workloads
                cap = env.get_int("MXNET_OP_CACHE_MAX")
                while cap and len(self._per_sig) > cap:
                    oldest = min(self._per_sig.items(),
                                 key=lambda kv: kv[1].tick)[0]
                    if oldest == sig:
                        break
                    del self._per_sig[oldest]
                    evicted += 1
        if evicted:  # counting/telemetry outside the instance lock
            with _jit_lock:
                _cache_evictions["ops_aot"] += evicted
            _tinstruments.compile_cache_evict_total("ops_aot").inc(
                evicted)
        return ent.fn(*args)


def jitted(op: Operator, attrs_key: Tuple) -> Callable:
    key = (op.name, attrs_key)
    fn = _cache_hit(_jit_cache, key)
    if fn is None:
        with _jit_lock:
            fn = _cache_hit(_jit_cache, key)
            if fn is None:
                attrs = thaw_attrs(attrs_key)
                fn = jax.jit(functools.partial(op.fn, **attrs))
                if _OPS_AOT:
                    # compiles (and records) per concrete signature
                    # inside the wrapper instead of here
                    fn = _AotDispatch(
                        f"ops.jit:{op.name}", fn, (op.name, attrs_key),
                        use_alias=_first_party_fn(op.fn))
                else:
                    # per-op site: a storm means ONE op's sigs churn
                    _mxsan.record_compile(f"ops.jit:{op.name}",
                                          attrs_key)
                _cache_insert_locked(_jit_cache, key, fn, "ops_jit")
    return fn


def grad_fn(op: Operator, attrs_key: Tuple, argnums: Tuple[int, ...]) -> Callable:
    """Jitted vjp: (inputs, cotangents) -> grads for `argnums` inputs."""
    key = (op.name, attrs_key, argnums)
    fn = _cache_hit(_grad_cache, key)
    if fn is None:
        with _jit_lock:
            fn = _cache_hit(_grad_cache, key)
            if fn is None:
                attrs = thaw_attrs(attrs_key)
                f = functools.partial(op.fn, **attrs)

                def _vjp(inputs, cts, _f=f, _argnums=argnums):
                    def fwd(*diff_ins):
                        full = list(inputs)
                        for i, a in zip(_argnums, diff_ins):
                            full[i] = a
                        return _f(*full)

                    _, vjp = jax.vjp(fwd, *[inputs[i] for i in _argnums])
                    return vjp(cts)

                fn = jax.jit(_vjp)
                if _OPS_AOT:
                    fn = _AotDispatch(
                        f"ops.grad:{op.name}", fn,
                        (op.name, attrs_key, argnums),
                        use_alias=_first_party_fn(op.fn))
                else:
                    _mxsan.record_compile(f"ops.grad:{op.name}",
                                          (attrs_key, argnums))
                _cache_insert_locked(_grad_cache, key, fn, "ops_grad")
    return fn


def apply_pure(name: str, *arrays, **attrs):
    """Run op on raw jax values — the path used inside traced (hybridized)
    programs, where inputs are jax tracers and no wrapping happens."""
    return get_op(name).fn(*arrays, **attrs)


# --------------------------------------------------------------------------
# Imperative invoke (ref: MXImperativeInvokeEx → Imperative::Invoke)
# --------------------------------------------------------------------------

def _op_dispatch_child(op: Operator):
    """Counter child cached on the Operator, keyed by the registry
    generation — enabled dispatch pays an attribute read + int compare
    per call, not the instruments lock; a registry clear() invalidates
    the cache via the generation bump."""
    gen = _tmetrics.get_registry().generation
    cached = getattr(op, "_tel_dispatch", None)
    if cached is not None and cached[0] == gen:
        return cached[1]
    child = _tinstruments.op_dispatch_total(op.name)
    op._tel_dispatch = (gen, child)
    return child


def dispatch(op: Operator, attrs_key: Tuple, arrays, attrs: dict):
    """The dispatch hot section of `invoke`.

    When neither the profiler nor telemetry is active this is ONE
    predicate check ahead of the cached-executable call — no context
    manager, no event append, no counter touch (the overhead gate in
    tests/test_telemetry.py holds this to the seed dispatch cost).
    """
    if not (_profiler._running or _tracing._ENABLED):
        if op.no_jit:
            return op.fn(*arrays, **attrs)
        return jitted(op, attrs_key)(*arrays)
    with _profiler.profile_op(op.name):
        if op.no_jit:
            out = op.fn(*arrays, **attrs)
        else:
            out = jitted(op, attrs_key)(*arrays)
    if _tracing._ENABLED:
        _op_dispatch_child(op).inc()
    return out

def invoke(op_name: str, *inputs, **attrs):
    """Imperative op call on NDArrays → NDArray(s).

    Mirrors CS1 in SURVEY.md: infer/alloc outputs (jax does this), record
    on the autograd tape if recording, async-dispatch the compiled
    executable (PjRt), return immediately.
    """
    from ..ndarray.ndarray import NDArray, wrap_outputs
    from .. import autograd as ag

    op = get_op(op_name)
    # an OPTIONAL array input (state=None, bias=None) passed by keyword
    # must become a positional input, not an attr — otherwise the array
    # would be frozen into the jit cache key and crash inside the trace
    nd_kw = {k: v for k, v in attrs.items() if isinstance(v, NDArray)}
    if nd_kw and getattr(op, "param_order", None):
        order = op.param_order
        unknown = [k for k in nd_kw if k not in order]
        if unknown:
            if op.allow_any_attr:
                nd_kw = {k: v for k, v in nd_kw.items() if k in order}
            else:
                raise MXNetError(
                    f"operator {op.name!r} has no input or attribute "
                    f"{unknown[0]!r}; array inputs: {op.input_names}, "
                    f"attributes: {sorted(op.attr_defaults)}")
        if nd_kw:
            last = max(order.index(k) for k in nd_kw)
            extra = []
            for name in order[len(inputs):last + 1]:
                if name in nd_kw:
                    attrs.pop(name)
                    extra.append(nd_kw[name])
                else:  # gap: fill the declared default (e.g. state=None)
                    extra.append(attrs.pop(name,
                                           op.param_default.get(name)))
            inputs = tuple(inputs) + tuple(extra)
    arrays = []
    ctx = None
    for x in inputs:
        if isinstance(x, NDArray):
            # ._data: the dense jax payload — for sparse NDArrays .data is
            # the values block (reference naming); generic ops see the
            # densified view (ref: FCompute fallback densifies FComputeEx
            # storage types)
            arrays.append(x._data)
            ctx = ctx or x.ctx
        else:
            arrays.append(x)
    attrs = op.validate_attrs(attrs)  # loud unknown-attr errors + coercion
    attrs_key = freeze_attrs(attrs)
    out = dispatch(op, attrs_key, arrays, attrs)
    if _NAIVE:
        from .. import engine as _engine

        if _engine.in_bulk():
            # bulking scope defers the synchronous wait to scope exit
            _engine._track(out if isinstance(out, (tuple, list)) else [out])
        else:
            jax.block_until_ready(out)
    results = wrap_outputs(out, ctx)
    if op.differentiable and ag.is_recording():
        ag.record_op(op, attrs_key, inputs, arrays, results)
    return results
