"""Cross-artifact drift checks — the cheap seventh pass.

Telemetry and chaos are only useful if the operator-facing docs list
what actually exists: an instrument nobody can find on a dashboard, or
a chaos site missing from the fault-model table, is drift the same way
a stale ``env_vars.md`` is.  These scanners are pure stdlib (AST +
regex over file bytes, no framework import) so both the mxlint CLI and
a tier-1 sync test can run them in milliseconds:

  * every metric family name registered in
    ``telemetry/instruments.py`` must appear in
    ``docs/observability.md``;
  * every ``chaos.check("<kind>")`` site in the package must appear in
    ``docs/resilience.md``'s fault-model table.
"""
from __future__ import annotations

import ast
import os
import re
from typing import List, Set

__all__ = ["instrument_names", "chaos_sites", "drift_findings"]

_CHAOS_RE = re.compile(r"chaos\.check\(\s*[\"']([a-z_.]+)[\"']")


def instrument_names(instruments_path: str) -> Set[str]:
    """Literal metric family names (``mx_*``) DECLARED in the
    instruments module — the ``_spec(...)`` declaration table (plus
    the legacy ``_child``/``_family`` literal form).  Names built by
    the declaration loop (``f"mx_serving_{n}_total"``) are out of
    AST reach here; the telemetry.catalog docs-sync test covers every
    declared name including those."""
    with open(instruments_path, "r", encoding="utf-8") as f:
        tree = ast.parse(f.read())
    names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Name) and \
                node.func.id in ("_spec", "_child", "_family") \
                and node.args:
            a = node.args[0]
            if isinstance(a, ast.Constant) and \
                    isinstance(a.value, str) and \
                    a.value.startswith("mx_"):
                names.add(a.value)
    return names


def chaos_sites(pkg_dir: str) -> Set[str]:
    """Every ``chaos.check("<kind>")`` literal in the package (the
    injection sites the fault-model table must list)."""
    sites: Set[str] = set()
    for dirpath, dirnames, filenames in os.walk(pkg_dir):
        dirnames[:] = [d for d in dirnames
                       if d not in ("__pycache__", ".git")]
        for fname in filenames:
            if not fname.endswith(".py"):
                continue
            try:
                with open(os.path.join(dirpath, fname), "r",
                          encoding="utf-8") as f:
                    text = f.read()
            except OSError:
                continue
            sites.update(_CHAOS_RE.findall(text))
    return sites


def drift_findings(repo_root: str) -> List[str]:
    """Human-readable drift findings ([] = in sync).  Missing docs
    files are reported as findings, not errors — a deleted doc IS
    drift."""
    out: List[str] = []
    ins_path = os.path.join(repo_root, "mxnet_tpu", "telemetry",
                            "instruments.py")
    obs_path = os.path.join(repo_root, "docs", "observability.md")
    res_path = os.path.join(repo_root, "docs", "resilience.md")
    pkg = os.path.join(repo_root, "mxnet_tpu")

    def read(path: str) -> str:
        try:
            with open(path, "r", encoding="utf-8") as f:
                return f.read()
        except OSError:
            out.append(f"{os.path.relpath(path, repo_root)}: missing")
            return ""

    obs = read(obs_path)
    if os.path.exists(ins_path):
        for name in sorted(instrument_names(ins_path)):
            if name not in obs:
                out.append(
                    f"instrument {name} (telemetry/instruments.py) is "
                    f"not documented in docs/observability.md")
    res = read(res_path)
    for site in sorted(chaos_sites(pkg)):
        if f"`{site}`" not in res and site not in res:
            out.append(
                f"chaos site {site!r} is not documented in "
                f"docs/resilience.md's fault-model table")
    return out
