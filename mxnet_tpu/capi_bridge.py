"""Python side of the minimal NDArray/op C ABI (src/ndarray_capi.cc).

Round-4 verdict item #8 closed the N14 "partial" by adding the smallest
surface a cpp-package-style consumer needs (ref: include/mxnet/c_api.h
MXNDArrayCreate / MXNDArraySyncCopyFromCPU / MXImperativeInvoke family):
create / free / copy in / copy out / invoke-any-registered-op.  On this
framework the runtime IS the Python process (JAX/PjRt owns the arrays),
so the C layer embeds-or-attaches to CPython and calls these helpers —
the TPU-native inversion of the reference, where Python wraps a C++
runtime.  Consumers: either a standalone C program linking
libpython3.x + build/libmxnet_tpu_capi.so, or any in-process FFI
(ctypes tests do exactly that).

Every helper speaks plain types (tuples, bytes, dicts of strings) so the
C side stays a thin argument-marshalling layer with no knowledge of
NDArray internals.
"""
from __future__ import annotations

from typing import Dict, List, Sequence

import numpy as np

from .base import MXNetError

__all__ = ["create", "copy_from", "copy_to", "shape_of", "dtype_of",
           "invoke", "deploy_load", "deploy_run"]


def _nd():
    from . import ndarray as nd

    return nd


def create(shape: Sequence[int], dtype: str = "float32"):
    """Zero-filled NDArray on the default context."""
    return _nd().zeros(tuple(int(s) for s in shape), dtype=dtype)


def copy_from(arr, buf: bytes) -> None:
    """Overwrite `arr` with raw C-order bytes (dtype/shape must match)."""
    want = int(np.prod(arr.shape, dtype=np.int64)) * \
        np.dtype(arr.dtype).itemsize
    if len(buf) != want:
        raise MXNetError(
            f"copy_from: got {len(buf)} bytes, array needs {want}")
    host = np.frombuffer(buf, dtype=arr.dtype).reshape(arr.shape)
    arr[:] = _nd().array(host, ctx=arr.ctx, dtype=str(arr.dtype))


def copy_to(arr) -> bytes:
    """Blocking device->host read of the full array as C-order bytes."""
    return np.ascontiguousarray(arr.asnumpy()).tobytes()


def shape_of(arr) -> tuple:
    return tuple(int(s) for s in arr.shape)


def dtype_of(arr) -> str:
    return str(arr.dtype)


def deploy_load(path: str):
    """Open a contrib.deploy StableHLO artifact for C-side serving —
    the full cpp-package-predictor equivalence (ref: c_predict_api.h
    MXPredCreate): artifact in, opaque served-model handle out."""
    from .contrib import deploy

    return deploy.import_model(path)


def deploy_run(served, inputs: List, seed: int = 0) -> List:
    """Run a served model on NDArray inputs; outputs FLATTENED in
    tree-flatten order (the C ABI is a flat-array surface — structure
    lives in the artifact's meta.json for consumers that care).  `seed`
    feeds the per-call PRNG key, so stochastic eval-mode layers draw
    fresh samples from C too."""
    import jax

    out = served(*inputs, seed=int(seed))
    flat, _ = jax.tree_util.tree_flatten(out)
    return list(flat)


def invoke(op_name: str, inputs: List, str_attrs: Dict[str, str]) -> List:
    """Run a registered operator imperatively (the C twin of
    nd.<op>(...)).  Attrs arrive as strings and are parsed with the same
    literal rules as `-symbol.json` attributes, so C callers spell them
    exactly like a saved symbol file does ("(3, 3)", "64", "relu")."""
    from .ndarray import register as nd_register
    from .symbol.symbol import _parse_attr_value

    fn = nd_register.lookup(op_name)
    attrs = {k: _parse_attr_value(v) for k, v in str_attrs.items()}
    out = fn(*inputs, **attrs)
    return list(out) if isinstance(out, (list, tuple)) else [out]
