"""Driver benchmark: ResNet-50 synthetic-data training throughput on one
chip (the BASELINE.md north-star workload: images/sec/chip, target = MXNet
ResNet-50 on 1xV100 ~= 375 img/s fp32).

The whole train step (forward, backward, grad reduce, SGD update, BatchNorm
stat update) is ONE jitted XLA program with donated buffers via
parallel.SPMDTrainer over a single-device mesh; compute in bfloat16 for the
MXU.

Prints exactly one JSON line:
  {"metric": ..., "value": N, "unit": "img/s", "vs_baseline": N}
"""
from __future__ import annotations

import argparse
import json
import sys
import time

V100_BASELINE_IMG_S = 375.0  # BASELINE.md: MXNet ResNet-50 fp32 on 1xV100


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=256)
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--warmup", type=int, default=3)
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--cpu-smoke", action="store_true",
                    help="tiny shapes on the CPU backend (CI self-test)")
    args = ap.parse_args()

    if args.cpu_smoke:
        import jax
        jax.config.update("jax_platforms", "cpu")
        args.batch_size, args.image_size = 8, 64
        args.steps, args.warmup = 3, 1

    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import parallel
    from mxnet_tpu.gluon import loss as gloss
    from mxnet_tpu.gluon.model_zoo import vision

    net = vision.resnet50_v1(classes=1000)
    net.initialize(mx.initializer.Xavier(magnitude=2.0), ctx=mx.cpu())
    with mx.autograd.pause():   # resolve deferred shapes (cheap spatial dims)
        net(mx.nd.zeros((1, 3, 32, 32), ctx=mx.cpu()))
    if args.dtype != "float32":
        net.cast(args.dtype)

    rng = np.random.RandomState(0)
    images = rng.rand(args.batch_size, 3, args.image_size,
                      args.image_size).astype(args.dtype)
    labels = rng.randint(0, 1000, size=(args.batch_size,)).astype(np.int32)

    mesh = parallel.make_mesh(dp=1)
    with mesh:
        trainer = parallel.SPMDTrainer(
            net, gloss.SoftmaxCrossEntropyLoss(), "sgd",
            {"learning_rate": 0.1, "momentum": 0.9, "wd": 1e-4})

        # synthetic-data convention (ref: image-classification --benchmark 1):
        # the batch lives on device; we measure the train step, not the
        # host link (which in this dev harness is a slow tunnel)
        images = trainer._place(images, None)
        labels = trainer._place(labels, None)

        for _ in range(args.warmup):
            loss = trainer.step(images, labels)
        loss.asnumpy()

        t0 = time.perf_counter()
        for _ in range(args.steps):
            loss = trainer.step(images, labels)
        lval = float(loss.asnumpy())  # blocks: full async chain done
        dt = time.perf_counter() - t0

    img_s = args.batch_size * args.steps / dt
    assert np.isfinite(lval), f"non-finite loss {lval}"
    print(json.dumps({
        "metric": "resnet50_v1_train_throughput_per_chip",
        "value": round(img_s, 2),
        "unit": "img/s",
        "vs_baseline": round(img_s / V100_BASELINE_IMG_S, 3),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
