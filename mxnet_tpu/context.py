"""Device contexts: cpu / tpu, with a thread-local `with ctx:` stack.

TPU-native counterpart of the reference's Context
(ref: include/mxnet/base.h Context{dev_type, dev_id};
python/mxnet/context.py Context/cpu()/gpu()/current_context()).

Here a Context maps onto a JAX device: ``tpu(i)`` is
``jax.devices('tpu')[i]``; ``cpu()`` is the host backend.  ``gpu(i)`` is
accepted for script compatibility and resolves to the accelerator backend
if one exists (so reference scripts with ``ctx=mx.gpu()`` run unmodified
on a TPU host).
"""
from __future__ import annotations

import threading
from typing import Optional

from .base import MXNetError

__all__ = ["Context", "cpu", "gpu", "tpu", "current_context", "num_tpus", "num_gpus"]


class Context:
    """A device context. devtype in {'cpu', 'tpu', 'gpu', 'cpu_pinned', 'cpu_shared'}."""

    # numeric ids kept stable with the reference's DeviceType enum where they
    # exist (kCPU=1, kGPU=2, kCPUPinned=3, kCPUShared=5); kTPU is new (=6).
    devtype2mask = {"cpu": 1, "gpu": 2, "cpu_pinned": 3, "cpu_shared": 5, "tpu": 6}
    devmask2type = {v: k for k, v in devtype2mask.items()}

    _default_ctx = threading.local()

    def __init__(self, device_type: str, device_id: int = 0):
        if isinstance(device_type, Context):
            device_type, device_id = device_type.device_type, device_type.device_id
        if device_type not in self.devtype2mask:
            raise MXNetError(f"unknown device type {device_type!r}")
        self.device_type = device_type
        self.device_id = int(device_id)
        self._old_ctx: Optional["Context"] = None

    @property
    def device_typeid(self) -> int:
        return self.devtype2mask[self.device_type]

    def __eq__(self, other):
        return (
            isinstance(other, Context)
            and self.device_type == other.device_type
            and self.device_id == other.device_id
        )

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def __repr__(self):
        return f"{self.device_type}({self.device_id})"

    __str__ = __repr__

    # --- with-stack (ref: python/mxnet/context.py __enter__/__exit__) ---
    def __enter__(self):
        self._old_ctx = getattr(Context._default_ctx, "value", None)
        Context._default_ctx.value = self
        return self

    def __exit__(self, *exc):
        Context._default_ctx.value = self._old_ctx
        return False

    # --- JAX mapping -------------------------------------------------
    @property
    def jax_device(self):
        """Resolve to a concrete jax.Device (lazy; import jax here)."""
        import jax

        # device ids are PER-PROCESS (local): in a multi-process (DCN) job
        # each worker addresses only its own devices — ctx cpu(0)/tpu(0)
        # must never resolve to another process's buffer space
        if self.device_type in ("cpu", "cpu_pinned", "cpu_shared"):
            devs = jax.local_devices(backend="cpu")
        else:
            devs = _accelerator_devices()
            if not devs:
                raise MXNetError(
                    f"context {self} requested but no accelerator devices present")
        if self.device_id >= len(devs):
            raise MXNetError(
                f"context {self}: device_id out of range ({len(devs)} present)")
        return devs[self.device_id]

    def empty_cache(self):
        """Reference API parity (Context.empty_cache). XLA manages HBM; no-op."""


def _accelerator_devices():
    import jax

    try:
        devs = jax.local_devices()
    except RuntimeError:
        return []
    return [d for d in devs if d.platform != "cpu"]


def cpu(device_id: int = 0) -> Context:
    return Context("cpu", device_id)


def gpu(device_id: int = 0) -> Context:
    """Compat alias: resolves to the accelerator backend (TPU here)."""
    return Context("gpu", device_id)


def tpu(device_id: int = 0) -> Context:
    return Context("tpu", device_id)


def cpu_pinned(device_id: int = 0) -> Context:
    return Context("cpu_pinned", device_id)


def cpu_shared(device_id: int = 0) -> Context:
    return Context("cpu_shared", device_id)


def num_tpus() -> int:
    return len(_accelerator_devices())


def num_gpus() -> int:
    """Compat: reference scripts probe mx.context.num_gpus()."""
    return len(_accelerator_devices())


def current_context() -> Context:
    """Thread-local current context; defaults to tpu(0) if present else cpu(0).

    The reference defaults to cpu(0); on a TPU host the accelerator is the
    natural default and reference scripts pass ctx explicitly anyway.
    Override with env MXNET_DEFAULT_CONTEXT=cpu|tpu.
    """
    cur = getattr(Context._default_ctx, "value", None)
    if cur is not None:
        return cur
    from .util import env

    forced = env.get_str("MXNET_DEFAULT_CONTEXT")
    if forced:
        return Context(forced, 0)
    return tpu(0) if num_tpus() > 0 else cpu(0)
