"""Cross-layer fused Conv+BN+ReLU unit: Pallas TPU kernel + XLA fallback.

The ResNet-50 train step is HBM-bound (PERF.md roofline: 85-95% of
achievable bandwidth at op granularity), so the remaining headroom is
activation *traffic*, not FLOPs.  The reference gets its version of this
from cuDNN fused conv epilogues + MKLDNN subgraph fusion (ref:
src/operator/subgraph/mkldnn/mkldnn_conv.cc fuses conv+BN+ReLU); the
TPU-native equivalent is this kernel.

The unit computes, for one conv layer k inside a conv->BN->ReLU chain:

    u  = act(x * in_scale + in_bias)        # layer k-1's BatchNorm+ReLU,
                                            # applied WHILE READING x (the
                                            # raw conv_{k-1} output) so the
                                            # normalized activation is never
                                            # materialized in HBM
    y  = conv(u, w)                         # this layer's conv (raw out)
    s1 = sum_c(y); s2 = sum_c((y-shift)^2)  # BN statistics of y, folded
                                            # into the conv epilogue so the
                                            # separate stats pass disappears

A chain of these units touches HBM twice per layer (read x, write y) vs
~5 passes/layer for the op-granular path (conv write, stats read,
normalize read+write, next-conv read).  `shift` is the running mean: the
variance uses the same shifted single-pass formula as ops/nn.py
`_batch_norm` (E[(y-c)^2] - (mean-c)^2, warm-stat exact, floor-bounded)
so fused and unfused training see identical statistics semantics.

Backward is hand-written XLA (not Pallas): dgrad/wgrad via
jax.linear_transpose of the forward conv (exactly the transpose convs
XLA autodiff would emit, with no forward recompute), the BN-stat
cotangents folded into dy (dy_tot = dy + g_s1 + 2(y-shift)g_s2), and the
input-affine/ReLU backward recomputed elementwise from x.  Residuals are
(inputs, y): y is the layer activation that the op-granular path would
have stored anyway, so fusion adds no activation memory.

The Pallas path needs layout NHWC (channels on the 128-lane axis) and a
TPU backend; everything else (CPU tests, NCHW, probe failure,
MXNET_USE_PALLAS=0) takes the XLA fallback with identical semantics.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..base import get_env
from .registry import register_op

__all__ = ["fused_conv_unit"]

_STATE = {"enabled": None}

# VMEM working-set budget for choosing the per-program batch tile
# (im2col block + double-buffered x/y grid blocks), leaving headroom for
# the weight panel and Mosaic's own scratch inside the 16MB core VMEM.
_COLS_BUDGET_BYTES = 8 * 1024 * 1024


def _pallas_wanted() -> bool:
    """Pallas usable?  Decided once: not on CPU (unless interpret mode is
    forced for tests) and only if a probe kernel actually compiles."""
    if _STATE["enabled"] is None:
        if not get_env("MXNET_USE_PALLAS", True, bool):
            _STATE["enabled"] = False
            return False
        try:
            backend = jax.default_backend()
        except Exception:
            backend = "cpu"
        interp = get_env("MXNET_PALLAS_INTERPRET", False, bool)
        if backend == "cpu" and not interp:
            _STATE["enabled"] = False
            return False
        try:
            x = jnp.zeros((2, 8, 8, 128), jnp.bfloat16)
            w = jnp.zeros((128, 128, 3, 3), jnp.bfloat16)
            sc = jnp.ones((128,), jnp.float32)
            sh = jnp.zeros((128,), jnp.float32)
            jax.eval_shape(functools.partial(
                _pallas_unit, kernel=(3, 3), stride=(1, 1), pad=(1, 1),
                act_in=True, want_stats=True), x, w, sc, sc, sh)
            if interp:
                _STATE["enabled"] = True
                return True
            jax.jit(functools.partial(
                _pallas_unit, kernel=(3, 3), stride=(1, 1), pad=(1, 1),
                act_in=True, want_stats=True)).lower(x, w, sc, sc, sh) \
                .compile()
            _STATE["enabled"] = True
        except Exception:
            _STATE["enabled"] = False
    return _STATE["enabled"]


def _batch_tile(n, h, w, ci, ho, wo, co, k_contract, itemsize=2):
    """Largest power-of-two batch tile dividing n whose whole VMEM
    working set fits the budget: im2col block + double-buffered x and y
    grid blocks (the y block dominates for 1x1 expansion convs where
    co >> kh*kw*ci).  >=1 even when one image overflows it: the
    56x56-stage im2col block is ~3.6MB and must still run.  `itemsize`
    is the activation dtype width (2 for bf16, 4 for fp32)."""
    per_image = (ho * wo * k_contract      # cols
                 + 2 * h * w * ci          # x block, double-buffered
                 + 2 * ho * wo * co) * itemsize  # y block, double-buffered
    nb = 1
    while nb * 2 <= n and n % (nb * 2) == 0 \
            and (nb * 2) * per_image <= _COLS_BUDGET_BYTES:
        nb *= 2
    return nb


def _out_hw(h, w, kernel, stride, pad):
    ho = (h + 2 * pad[0] - kernel[0]) // stride[0] + 1
    wo = (w + 2 * pad[1] - kernel[1]) // stride[1] + 1
    return ho, wo


def _im2col(u, kernel, stride, pad, ho, wo):
    """(NB,H,W,C) -> (NB*Ho*Wo, kh*kw*C) patches, (ky,kx,c) minor order —
    must match the weight panel layout in `_weight_panel`."""
    kh, kw = kernel
    sh, sw = stride
    if pad != (0, 0):
        u = jnp.pad(u, ((0, 0), (pad[0], pad[0]), (pad[1], pad[1]), (0, 0)))
    if (kh, kw) == (1, 1):
        cols = u[:, ::sh, ::sw, :]
    else:
        slices = []
        for ky in range(kh):
            for kx in range(kw):
                slices.append(
                    u[:, ky:ky + (ho - 1) * sh + 1:sh,
                      kx:kx + (wo - 1) * sw + 1:sw, :])
        cols = jnp.concatenate(slices, axis=-1)
    return cols.reshape(cols.shape[0] * ho * wo, -1)


def _weight_panel(w):
    """(Co, Ci, kh, kw) checkpoint layout -> (kh*kw*Ci, Co) matmul panel."""
    return jnp.transpose(w, (2, 3, 1, 0)).reshape(-1, w.shape[0])


# ---------------------------------------------------------------------------
# Pallas forward
# ---------------------------------------------------------------------------

def _pallas_unit(x, w, in_scale, in_bias, shift, *, kernel, stride, pad,
                 act_in, want_stats):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, h, wd, ci = x.shape
    co = w.shape[0]
    ho, wo = _out_hw(h, wd, kernel, stride, pad)
    nb = _batch_tile(n, h, wd, ci, ho, wo, co, kernel[0] * kernel[1] * ci,
                     itemsize=x.dtype.itemsize)
    wmat = _weight_panel(w)
    out_dtype = x.dtype

    def kern(x_ref, w_ref, sc_ref, bi_ref, sh_ref, y_ref, s1_ref, s2_ref):
        xb = x_ref[...]
        if act_in:
            u = xb.astype(jnp.float32) * sc_ref[...] + bi_ref[...]
            u = jnp.maximum(u, 0.0).astype(xb.dtype)
        else:
            u = xb
        cols = _im2col(u, kernel, stride, pad, ho, wo)
        y = jnp.dot(cols, w_ref[...], preferred_element_type=jnp.float32)
        yc = y.astype(out_dtype)
        y_ref[...] = yc.reshape(nb, ho, wo, co)
        # the stat outputs must be written in EVERY mode — an output
        # block left untouched returns whatever was in VMEM (the XLA
        # fallback returns zeros for want_stats=False; match it)
        @pl.when(pl.program_id(0) == 0)
        def _():
            s1_ref[...] = jnp.zeros_like(s1_ref)
            s2_ref[...] = jnp.zeros_like(s2_ref)

        if want_stats:
            # stats of the STORED (cast) value, accumulated fp32 across
            # the sequential grid — semantics identical to the unfused
            # BatchNorm reading the bf16 activation back from HBM
            yf = yc.astype(jnp.float32)
            d = yf - sh_ref[...]
            s1_ref[...] += jnp.sum(yf, axis=0, keepdims=True)
            s2_ref[...] += jnp.sum(d * d, axis=0, keepdims=True)

    grid = (n // nb,)
    y, s1, s2 = pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            pl.BlockSpec((nb, h, wd, ci), lambda i: (i, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((wmat.shape[0], co), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, ci), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, ci), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, co), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((nb, ho, wo, co), lambda i: (i, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, co), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, co), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, ho, wo, co), out_dtype),
            jax.ShapeDtypeStruct((1, co), jnp.float32),
            jax.ShapeDtypeStruct((1, co), jnp.float32),
        ],
        interpret=get_env("MXNET_PALLAS_INTERPRET", False, bool),
    )(x, wmat, in_scale.reshape(1, ci), in_bias.reshape(1, ci),
      shift.reshape(1, co))
    return y, s1.reshape(co), s2.reshape(co)


# ---------------------------------------------------------------------------
# XLA fallback (identical semantics) + shared backward
# ---------------------------------------------------------------------------

def _apply_in_affine(x, in_scale, in_bias, act_in):
    if not act_in:
        return x
    u = (x.astype(jnp.float32) * in_scale.reshape(1, 1, 1, -1)
         + in_bias.reshape(1, 1, 1, -1))
    return jnp.maximum(u, 0.0).astype(x.dtype)


def _conv_nhwc(u, w_hwio, stride, pad):
    return lax.conv_general_dilated(
        u, w_hwio, window_strides=stride,
        padding=[(pad[0], pad[0]), (pad[1], pad[1])],
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def _xla_unit(x, w, in_scale, in_bias, shift, *, kernel, stride, pad,
              act_in, want_stats):
    u = _apply_in_affine(x, in_scale, in_bias, act_in)
    y = _conv_nhwc(u, jnp.transpose(w, (2, 3, 1, 0)), stride, pad)
    if want_stats:
        yf = y.astype(jnp.float32)
        s1 = jnp.sum(yf, axis=(0, 1, 2))
        d = yf - shift.reshape(1, 1, 1, -1)
        s2 = jnp.sum(d * d, axis=(0, 1, 2))
    else:
        co = y.shape[-1]
        s1 = jnp.zeros((co,), jnp.float32)
        s2 = jnp.zeros((co,), jnp.float32)
    return y, s1, s2


# Trace-time success does NOT imply the kernel will survive Mosaic
# lowering (that happens later, when the enclosing jitted program
# compiles, far outside any try/except here).  So each distinct
# (shapes, statics) configuration is probe-COMPILED standalone once —
# with fresh ShapeDtypeStructs, never tracers, so it is safe to do in
# the middle of an outer trace — and configurations Mosaic rejects are
# pinned to the XLA fallback.
_SHAPE_OK: dict = {}
_PROBE_SPENT = [0.0]  # cumulative probe-compile seconds


def _shape_supported(x, w, kernel, stride, pad, act_in, want_stats) -> bool:
    key = (x.shape, str(x.dtype), w.shape, kernel, stride, pad, act_in,
           want_stats)
    ok = _SHAPE_OK.get(key)
    if ok is None:
        import time as _time

        budget = get_env("MXNET_PALLAS_PROBE_BUDGET", 300.0, float)
        if get_env("MXNET_PALLAS_INTERPRET", False, bool):
            ok = True  # interpreter mode has no Mosaic stage
        elif _PROBE_SPENT[0] >= budget:
            # probe time is bounded: ~20+ unique ResNet shapes at
            # ~10s/compile could otherwise eat the bench child's
            # timeout; shapes past the budget take the safe XLA
            # fallback (the traffic-heavy early layers probe first in
            # trace order).  NOT cached: 'never probed' must stay
            # distinguishable from 'Mosaic rejected' so a later call
            # with budget headroom can still probe this shape
            return False
        else:
            _t0 = _time.perf_counter()
            try:
                args = [jax.ShapeDtypeStruct(x.shape, x.dtype),
                        jax.ShapeDtypeStruct(w.shape, w.dtype),
                        jax.ShapeDtypeStruct((x.shape[-1],), jnp.float32),
                        jax.ShapeDtypeStruct((x.shape[-1],), jnp.float32),
                        jax.ShapeDtypeStruct((w.shape[0],), jnp.float32)]
                jax.jit(functools.partial(
                    _pallas_unit, kernel=kernel, stride=stride, pad=pad,
                    act_in=act_in, want_stats=want_stats)) \
                    .lower(*args).compile()
                ok = True
            except Exception:
                ok = False
            finally:
                _PROBE_SPENT[0] += _time.perf_counter() - _t0
        _SHAPE_OK[key] = ok
    return ok


def _multi_device_trace() -> bool:
    """True when tracing under a multi-device mesh: GSPMD cannot
    partition a pallas_call (that needs an explicit shard_map), so the
    fused unit must take the XLA fallback there — the fallback is plain
    XLA ops and partitions fine.  Single chip (the bench/dryrun dp=1
    mesh) keeps the Pallas kernel."""
    try:
        from ..parallel.mesh import current_mesh

        m = current_mesh()
        return m is not None and m.mesh.size > 1
    except Exception:
        return False


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _unit(x, w, in_scale, in_bias, shift, kernel, stride, pad, act_in,
          want_stats):
    if _pallas_wanted() and not _multi_device_trace() \
            and _shape_supported(x, w, kernel, stride, pad,
                                 act_in, want_stats):
        try:
            return _pallas_unit(x, w, in_scale, in_bias, shift,
                                kernel=kernel, stride=stride, pad=pad,
                                act_in=act_in, want_stats=want_stats)
        except Exception:
            pass
    return _xla_unit(x, w, in_scale, in_bias, shift, kernel=kernel,
                     stride=stride, pad=pad, act_in=act_in,
                     want_stats=want_stats)


def _unit_fwd(x, w, in_scale, in_bias, shift, kernel, stride, pad, act_in,
              want_stats):
    out = _unit(x, w, in_scale, in_bias, shift, kernel, stride, pad,
                act_in, want_stats)
    # y rides along as a residual: it is the stored activation either way
    return out, (x, w, in_scale, in_bias, shift, out[0])


def _unit_bwd(kernel, stride, pad, act_in, want_stats, res, cots):
    x, w, in_scale, in_bias, shift, y = res
    gy, gs1, gs2 = cots
    if want_stats:
        # fold the BN-stat cotangents into dy: d(s1)/dy = 1,
        # d(s2)/dy = 2(y - shift); all C-sized broadcasts, XLA fuses
        # this into the transpose-conv input reads
        gy_tot = (gy.astype(jnp.float32)
                  + gs1.reshape(1, 1, 1, -1)
                  + 2.0 * (y.astype(jnp.float32)
                           - shift.reshape(1, 1, 1, -1))
                  * gs2.reshape(1, 1, 1, -1)).astype(gy.dtype)
    else:
        gy_tot = gy
    u = _apply_in_affine(x, in_scale, in_bias, act_in)
    w_hwio = jnp.transpose(w, (2, 3, 1, 0))
    # dgrad / wgrad as the EXACT transpose of the forward conv — no
    # forward recompute (linear_transpose only traces abstractly)
    du = jax.linear_transpose(
        lambda l: _conv_nhwc(l, w_hwio, stride, pad), u)(gy_tot)[0]
    dw_hwio = jax.linear_transpose(
        lambda r: _conv_nhwc(u, r, stride, pad), w_hwio)(gy_tot)[0]
    dw = jnp.transpose(dw_hwio, (3, 2, 0, 1)).astype(w.dtype)
    if act_in:
        uf = (x.astype(jnp.float32) * in_scale.reshape(1, 1, 1, -1)
              + in_bias.reshape(1, 1, 1, -1))
        mask = uf > 0.0
        gu = jnp.where(mask, du.astype(jnp.float32), 0.0)
        gx = (gu * in_scale.reshape(1, 1, 1, -1)).astype(x.dtype)
        gscale = jnp.sum(gu * x.astype(jnp.float32), axis=(0, 1, 2))
        gbias = jnp.sum(gu, axis=(0, 1, 2))
    else:
        gx = du.astype(x.dtype)
        gscale = jnp.zeros_like(in_scale)
        gbias = jnp.zeros_like(in_bias)
    # shift is a running statistic (stop-gradient, like _batch_norm's c)
    return gx, dw, gscale, gbias, jnp.zeros_like(shift)


_unit.defvjp(_unit_fwd, _unit_bwd)


@register_op("FusedConvUnit")
def fused_conv_unit(data, weight, in_scale=None, in_bias=None, shift=None,
                    kernel=(1, 1), stride=(1, 1), pad=(0, 0), act_in=False,
                    want_stats=True):
    """Fused (input-affine+ReLU) -> conv -> (BN stats) unit, NHWC.

    data (N,H,W,Ci) raw previous-layer conv output; weight (Co,Ci,kh,kw)
    in the layout-independent checkpoint layout; in_scale/in_bias the
    fp32 per-channel affine that normalizes `data` (None = identity);
    shift the fp32 variance shift for this layer's stats (the running
    mean; None = zeros).  Returns (y_raw, s1, s2) with s1/s2 fp32
    per-channel sum / shifted sum-of-squares of y_raw.
    """
    kernel = tuple(int(k) for k in kernel)
    stride = tuple(int(s) for s in stride)
    pad = tuple(int(p) for p in pad)
    ci = data.shape[-1]
    co = weight.shape[0]
    if in_scale is None:
        in_scale = jnp.ones((ci,), jnp.float32)
    if in_bias is None:
        in_bias = jnp.zeros((ci,), jnp.float32)
    if shift is None:
        shift = jnp.zeros((co,), jnp.float32)
    return _unit(data, weight, in_scale.astype(jnp.float32),
                 in_bias.astype(jnp.float32), shift.astype(jnp.float32),
                 kernel, stride, pad, bool(act_in), bool(want_stats))
