"""SSD training example (BASELINE config 4: SSD-ResNet50).

Synthetic-data training loop over the full detection stack: SSD model →
SSDTargetGenerator (MultiBoxTarget) → SSDMultiBoxLoss → Trainer, then
MultiBoxDetection decode.  The reference-era equivalent is
example/ssd/train.py.

Usage:
  python examples/ssd_train.py                 # TPU, resnet50 backbone
  python examples/ssd_train.py --cpu --small   # CPU smoke (CI)
  python tools/im2rec.py voc train.lst /data/VOCdevkit --pack-label ...
  python examples/ssd_train.py --rec voc.rec --epochs 10
      # REAL-DATA path: RecordIO shards with packed object labels
      # (im2rec --pack-label), decoded by image.ImageDetIter
"""
from __future__ import annotations

import argparse
import time


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--small", action="store_true",
                    help="mobilenet backbone, 128px, for smoke tests")
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--classes", type=int, default=20)
    ap.add_argument("--no-hybridize", action="store_true")
    ap.add_argument("--rec", default=None,
                    help=".rec file with im2rec --pack-label object "
                         "labels (real-data path via ImageDetIter)")
    ap.add_argument("--epochs", type=int, default=1)
    args = ap.parse_args()

    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")

    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, nd
    from mxnet_tpu.gluon import Trainer
    from mxnet_tpu.gluon.model_zoo.detection import (
        SSDMultiBoxLoss, SSDTargetGenerator, get_detection_model)

    ctx = mx.cpu() if args.cpu else mx.tpu(0)
    size = 128 if args.small else 300
    name = "ssd_300_mobilenet1.0" if args.small else "ssd_300_resnet50_v1"
    net = get_detection_model(name, classes=args.classes)
    net.initialize(mx.initializer.Xavier(), ctx=ctx)
    if not args.no_hybridize:
        net.hybridize(static_alloc=True)

    target_gen = SSDTargetGenerator()
    loss_fn = SSDMultiBoxLoss()
    trainer = Trainer(net.collect_params(), "sgd",
                      {"learning_rate": 1e-3, "momentum": 0.9, "wd": 5e-4})

    def train_step(x, labels, step):
        tic = time.time()
        with autograd.record():
            cls_preds, box_preds, anchors = net(x)
            box_t, _box_m, cls_t = target_gen(anchors, labels, cls_preds)
            loss = loss_fn(cls_preds, box_preds, cls_t, box_t)
        loss.backward()
        trainer.step(args.batch_size)
        lval = float(loss.asnumpy().mean())
        print(f"step {step}: loss={lval:.4f} ({time.time() - tic:.2f}s)")
        return cls_preds, box_preds, anchors

    rng = np.random.RandomState(0)
    if args.rec:
        from mxnet_tpu.image import CreateDetAugmenter, ImageDetIter

        it = ImageDetIter(
            batch_size=args.batch_size, data_shape=(3, size, size),
            path_imgrec=args.rec,
            aug_list=CreateDetAugmenter((3, size, size),
                                        rand_mirror=True, mean=True,
                                        std=True))
        step = 0
        for _ in range(args.epochs):
            it.reset()
            for batch in it:
                # packed labels are [cls, x1, y1, x2, y2] already in
                # relative corner coords — the target generator's format
                x = batch.data[0].as_in_context(ctx)
                labels = batch.label[0].as_in_context(ctx)
                cls_preds, box_preds, anchors = train_step(x, labels,
                                                           step)
                step += 1
    else:
        x = nd.array(
            rng.randn(args.batch_size, 3, size, size).astype("float32"),
            ctx=ctx)
        labels = nd.array(
            np.stack([[[rng.randint(args.classes), 0.2, 0.2, 0.7, 0.7]]
                      for _ in range(args.batch_size)]).astype("float32"),
            ctx=ctx)
        for step in range(args.steps):
            cls_preds, box_preds, anchors = train_step(x, labels, step)

    # decode detections for the final batch
    out = nd.MultiBoxDetection(
        nd.transpose(nd.softmax(cls_preds, axis=-1), axes=(0, 2, 1)),
        nd.reshape(box_preds, shape=(0, -1)), anchors, nms_topk=100)
    kept = (out.asnumpy()[:, :, 0] >= 0).sum()
    print(f"decoded {out.shape} detections, {kept} kept after NMS")


if __name__ == "__main__":
    main()
