"""KVStore: the data-parallel gradient-sync layer.

TPU-native counterpart of src/kvstore/** and python/mxnet/kvstore.py.
The reference has three transports behind one API (in-process reduce,
NCCL allreduce, ps-lite parameter server).  Here there is ONE collective
substrate — XLA collectives — behind the same API:

  * 'local' / 'device'  — in-process reduction across the NDArray replicas
    the caller hands in (ref: src/kvstore/kvstore_local.cc + comm.h).
  * 'xla' ('nccl' accepted as a compat alias — ref kvstore_nccl.h) —
    same API; when running under an SPMD mesh (mxnet_tpu.parallel) the
    reduction is an in-graph psum over ICI, which XLA fuses into the
    step; eagerly it falls back to the local reduce.
  * 'dist_sync' / 'dist_device_sync' / 'dist_async' — multi-process over
    DCN via jax.distributed (see mxnet_tpu.parallel.dist); push/pull map
    onto process-group allreduce.  dist_async is served by the same path
    (documented emulation: sync semantics are a superset).

set_optimizer/updater semantics (server-side optimizer when
update_on_kvstore, ref kvstore_dist_server.h) are preserved.
"""
from __future__ import annotations

import functools
import pickle
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp

from .base import MXNetError
from .ndarray.ndarray import NDArray
from . import optimizer as opt_mod

__all__ = ["KVStore", "create"]


def _as_list(x):
    return list(x) if isinstance(x, (list, tuple)) else [x]


class KVStore:
    def __init__(self, kind: str):
        self._kind = kind
        self._store: Dict[Union[int, str], NDArray] = {}
        self._updater: Optional[Callable] = None
        self._optimizer: Optional[opt_mod.Optimizer] = None
        self._compression = None

    # ---- identity --------------------------------------------------------
    @property
    def type(self) -> str:
        return self._kind

    @property
    def rank(self) -> int:
        if self._kind.startswith("dist"):
            return jax.process_index()
        return 0

    @property
    def num_workers(self) -> int:
        if self._kind.startswith("dist"):
            return jax.process_count()
        return 1

    # ---- core API --------------------------------------------------------
    def init(self, key, value):
        keys, values = self._normalize(key, value)
        for k, v in zip(keys, values):
            vv = v[0] if isinstance(v, list) else v
            self._store[k] = vv.copy()

    def push(self, key, value, priority: int = 0):
        keys, values = self._normalize(key, value)
        for k, v in zip(keys, values):
            agg = self._reduce(_as_list(v))
            if self._kind.startswith("dist"):
                agg = self._dcn_allreduce(agg, key=k)
            elif self._check_compressible(agg):
                agg = self._compress_roundtrip(k, agg)
            if self._updater is not None:
                if k not in self._store:
                    raise MXNetError(f"kvstore key {k} not initialized")
                self._updater(_key_int(k), agg, self._store[k])
            else:
                self._store[k] = agg

    def pull(self, key, out=None, priority: int = 0, ignore_sparse=True):
        from .ndarray.sparse import BaseSparseNDArray

        keys, outs = self._normalize(key, out)
        for k, o in zip(keys, outs):
            if k not in self._store:
                raise MXNetError(f"kvstore key {k} not initialized")
            src = self._store[k]
            for dst in _as_list(o):
                if isinstance(dst, BaseSparseNDArray):
                    raise MXNetError(
                        "pull with a sparse out is not supported; use "
                        "row_sparse_pull (ref: KVStoreLocal::PullImpl)")
                # ._data: the dense payload (for sparse src, .data is the
                # values block — reference naming)
                dst._data = src.as_in_context(dst.ctx)._data

    def pushpull(self, key, value, out=None, priority: int = 0):
        """Fused push+pull (ref: MXKVStorePushPullEx). Without an updater
        this is a pure allreduce — the hot path for Trainer."""
        from .ndarray.sparse import BaseSparseNDArray

        keys, values = self._normalize(key, value)
        _, outs = self._normalize(key, out if out is not None else value)
        for k, v, o in zip(keys, values, outs):
            agg = self._reduce(_as_list(v))
            if self._kind.startswith("dist"):
                agg = self._dcn_allreduce(agg, key=k)
            elif self._check_compressible(agg):
                agg = self._compress_roundtrip(k, agg)
            if self._updater is not None:
                if k not in self._store:
                    raise MXNetError(f"kvstore key {k} not initialized")
                self._updater(_key_int(k), agg, self._store[k])
                agg = self._store[k]
            for dst in _as_list(o):
                if isinstance(dst, BaseSparseNDArray):
                    raise MXNetError(
                        "pushpull with a sparse out is not supported; use "
                        "push + row_sparse_pull")
                dst._data = agg.as_in_context(dst.ctx)._data

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """ref: kvstore row_sparse_pull — pull only the requested rows.

        When `out` is a RowSparseNDArray the result is a real sparse pull:
        its indices become the (sorted, deduplicated) row_ids and only
        those rows carry values. Dense `out` gets the row-gathered dense
        emulation."""
        from .ndarray.sparse import RowSparseNDArray

        if row_ids is None:
            return self.pull(key, out, priority)
        keys, outs = self._normalize(key, out)
        _, rid_groups = self._normalize(key, row_ids)
        for k, o, rid_group in zip(keys, outs, rid_groups):
            if k not in self._store:
                raise MXNetError(f"kvstore key {k} not initialized")
            src = self._store[k]
            for dst, rid in zip(_as_list(o), _as_list(rid_group)):
                uniq = jnp.unique(rid._data.astype(jnp.int32))
                rows = jnp.take(src._data, uniq, axis=0)
                full = jnp.zeros(src.shape,
                                 src._data.dtype).at[uniq].set(rows)
                dev = dst.ctx.jax_device
                dst._data = jax.device_put(full, dev)
                if isinstance(dst, RowSparseNDArray):
                    dst._aux = {"indices": jax.device_put(uniq, dev)}

    # ---- optimizer hookup -----------------------------------------------
    def set_optimizer(self, optimizer: opt_mod.Optimizer):
        self._optimizer = optimizer
        self._updater = opt_mod.get_updater(optimizer)

    def set_updater(self, updater: Callable):
        self._updater = updater

    def set_gradient_compression(self, compression_params: dict):
        """2-bit gradient compression on the DCN (dist) push path
        (ref: GradientCompression, gradient_compression.cc): quantize to
        {0, ±threshold} with residual accumulation, 4 elements/byte on
        the wire.  Unknown types raise.  The ICI/SPMD path keeps
        uncompressed in-graph collectives by design."""
        from . import kvstore_compression

        if self._kind == "local":
            # reference parity: KVStoreLocal rejects compression; device/
            # dist stores accept it
            raise MXNetError(
                "gradient compression is not supported on 'local' "
                "kvstore (ref: KVStoreLocal::SetGradientCompression)")
        self._compression = kvstore_compression.create(compression_params)

    def save_optimizer_states(self, fname: str, dump_optimizer=False):
        if self._updater is None:
            raise MXNetError("no optimizer set on kvstore")
        with open(fname, "wb") as f:
            f.write(self._updater.get_states(dump_optimizer))

    def load_optimizer_states(self, fname: str):
        if self._updater is None:
            raise MXNetError("no optimizer set on kvstore")
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())

    def barrier(self):
        if self._kind.startswith("dist"):
            from .parallel import dist

            dist.barrier()

    # ---- internals -------------------------------------------------------
    def _reduce(self, vals: List[NDArray]) -> NDArray:
        """Local reduction across device replicas (ref: comm.h CommDevice;
        row_sparse inputs reduce to a row_sparse with merged indices, like
        the reference's sparse CommCPU path).  Dense reduction is ONE
        jitted balanced-tree sum, not a sequential add chain."""
        from .ndarray.sparse import RowSparseNDArray

        if len(vals) == 1:
            return vals[0].copy()
        dev = vals[0].ctx.jax_device
        parts = []
        for v in vals:
            d = v._data if isinstance(v, RowSparseNDArray) else v.data
            if list(d.devices()) != [dev]:
                d = jax.device_put(d, dev)
            parts.append(d)
        acc = _tree_sum(len(parts))(*parts)
        if all(isinstance(v, RowSparseNDArray) for v in vals):
            merged = jnp.sort(jnp.unique(jnp.concatenate(
                [jax.device_put(v._aux["indices"], dev) for v in vals])))
            return RowSparseNDArray(acc, {"indices": merged},
                                    ctx=vals[0].ctx)
        return NDArray(acc, ctx=vals[0].ctx)

    def _compress_nd(self, key, val: NDArray):
        """Quantize one dense NDArray -> (packed codes, shape)."""
        import numpy as np

        return self._compression.compress(
            key, np.asarray(jax.device_get(val.data)))

    def _compress_roundtrip(self, key, val: NDArray) -> NDArray:
        """Quantize+dequantize on a device-style store — the wire effect
        of 2-bit compression without a wire (ref: device-kvstore
        inter-GPU compression)."""
        packed, shape = self._compress_nd(key, val)
        return NDArray(jnp.asarray(
            self._compression.decompress(packed, shape)), ctx=val.ctx)

    def _check_compressible(self, val) -> bool:
        from .ndarray.sparse import BaseSparseNDArray

        if self._compression is None:
            return False
        if isinstance(val, BaseSparseNDArray):
            # reference parity: row_sparse + compression fails loud, it
            # never silently sends full-size gradients
            raise MXNetError(
                "gradient compression does not support sparse gradients "
                "(ref: GradientCompression row_sparse check)")
        return True

    def _dcn_allreduce(self, val: NDArray, key=None) -> NDArray:
        from .parallel import dist

        if key is not None and self._check_compressible(val):
            packed, shape = self._compress_nd(key, val)
            gathered = dist.allgather_np(packed)
            total = sum(self._compression.decompress(g, shape)
                        for g in gathered)
            return NDArray(jnp.asarray(total), ctx=val.ctx)
        return dist.allreduce_nd(val)

    def _normalize(self, key, value):
        keys = _as_list(key)
        if value is None:
            return keys, [None] * len(keys)
        if len(keys) == 1:
            return keys, [value]
        vals = _as_list(value)
        if len(vals) != len(keys):
            # grouped: values per key are lists
            raise MXNetError("key/value length mismatch")
        return keys, vals

    def __repr__(self):
        return f"KVStore(type={self._kind}, keys={len(self._store)})"


def _key_int(k):
    try:
        return int(k)
    except (TypeError, ValueError):
        return abs(hash(k)) % (2 ** 31)


@functools.lru_cache(maxsize=None)
def _tree_sum(n: int):
    """One fused XLA program summing n same-shaped arrays pairwise."""

    def balanced(xs):
        while len(xs) > 1:
            nxt = [xs[i] + xs[i + 1] for i in range(0, len(xs) - 1, 2)]
            if len(xs) % 2:
                nxt.append(xs[-1])
            xs = nxt
        return xs[0]

    return jax.jit(lambda *xs: balanced(list(xs)))


_VALID = {"local", "device", "xla", "nccl", "dist", "dist_sync", "dist_async",
          "dist_device_sync"}


_ASYNC_WARNED = [False]


def create(name: str = "local") -> KVStore:
    """ref: kvstore.create / KVStore::Create factory."""
    if name not in _VALID:
        raise MXNetError(f"unknown kvstore type {name!r}; valid: {sorted(_VALID)}")
    if name == "nccl":
        name = "xla"  # compat alias: the ICI collective store
    if name == "dist_async" and not _ASYNC_WARNED[0]:
        # one-time, loud: the staleness semantics a dist_async user
        # tuned for (hogwild-style non-blocking pushes) do not exist on
        # this backend — updates are synchronous collectives (see
        # docs/distributed.md, SURVEY.md §7 hard-part 6)
        import warnings

        warnings.warn(
            "kvstore 'dist_async' is emulated as 'dist_sync' on the TPU "
            "backend: pushes are synchronous XLA collectives, so there "
            "is no gradient staleness. Convergence behavior tuned for "
            "async PS training may differ.", UserWarning, stacklevel=2)
        _ASYNC_WARNED[0] = True
    return KVStore(name)
