"""Reference API-surface probe: the spellings real MXNet-1.x scripts
use must resolve (modules, aliases, namespaces, common helpers).  Pure
attribute resolution — numeric behavior is covered elsewhere."""
import numpy as np

import mxnet_tpu as mx

PROBES = [
    # module aliases
    "mx.nd", "mx.sym", "mx.mod.Module", "mx.viz.plot_network",
    "mx.kv.create", "mx.rnn.LSTMCell", "mx.rnn.BucketSentenceIter",
    # contrib namespaces
    "mx.nd.contrib.box_nms", "mx.sym.contrib.BilinearResize2D",
    "mx.contrib.ndarray.box_iou", "mx.contrib.symbol.ROIAlign",
    # frequently-used helpers
    "mx.metric.create", "mx.initializer.Uniform", "mx.initializer.Constant",
    "mx.random.uniform", "mx.random.normal", "mx.random.randint",
    "mx.random.seed", "mx.test_utils.list_gpus",
    "mx.gluon.utils.split_and_load", "mx.gluon.utils.clip_global_norm",
    "mx.gluon.nn.HybridLambda", "mx.gluon.rnn.ZoneoutCell",
    "mx.gluon.loss.CTCLoss", "mx.callback.Speedometer",
    "mx.io.NDArrayIter", "mx.io.PrefetchingIter",
    "mx.image.imdecode", "mx.image.CreateAugmenter",
    "mx.model.load_checkpoint", "mx.monitor.Monitor",
    "mx.profiler.set_config", "mx.engine.bulk", "mx.attribute.AttrScope",
    "mx.sym.MakeLoss", "mx.sym.BlockGrad", "mx.sym.Group",
    "mx.nd.one_hot", "mx.nd.topk", "mx.nd.where", "mx.nd.random.uniform",
]


def test_reference_spellings_resolve():
    missing = []
    for p in PROBES:
        obj = mx
        try:
            for part in p.split(".")[1:]:
                obj = getattr(obj, part)
        except AttributeError:
            missing.append(p)
    assert not missing, f"reference spellings missing: {missing}"


def test_ndarray_and_symbol_method_surface():
    from mxnet_tpu import nd

    x = nd.array(np.array([[3.0, 1.0, 2.0]], "float32"))
    for m in ("sort", "argsort", "topk", "sign", "floor", "ceil",
              "zeros_like", "ones_like", "slice_like"):
        assert hasattr(x, m), m
    np.testing.assert_array_equal(x.sort(axis=1).asnumpy(),
                                  [[1.0, 2.0, 3.0]])
    s = mx.sym.Variable("a")
    fc = mx.sym.FullyConnected(s, num_hidden=4, name="fc")
    assert fc.list_attr().get("num_hidden") == "4"
    assert "fc" in fc.attr_dict()
    assert "FullyConnected" in fc.debug_str()


def test_module_level_samplers():
    mx.random.seed(7)
    u = mx.random.uniform(-1, 1, shape=(3, 4))
    n = mx.random.normal(2.0, 0.5, shape=(64,))
    r = mx.random.randint(0, 5, shape=(32,))
    a = u.asnumpy()
    assert a.shape == (3, 4) and (a >= -1).all() and (a <= 1).all()
    assert abs(float(n.asnumpy().mean()) - 2.0) < 0.5
    rv = r.asnumpy()
    assert rv.min() >= 0 and rv.max() < 5


def test_sampler_out_kwarg_fills_in_place():
    import pytest

    from mxnet_tpu import nd
    from mxnet_tpu.base import MXNetError

    arr = nd.zeros((4,))
    ret = mx.random.uniform(1.0, 2.0, shape=(4,), out=arr)
    assert ret is arr
    a = arr.asnumpy()
    assert (a >= 1.0).all() and (a <= 2.0).all()
    # the reference idiom: shape defaults FROM out (no shape arg)
    w = nd.zeros((100,))
    mx.random.uniform(-1, 1, out=w)
    assert w.shape == (100,) and float(np.abs(w.asnumpy()).max()) > 0
    # nd.random spelling honors out= identically
    v = nd.zeros((8,))
    nd.random.normal(0.0, 1.0, out=v)
    assert float(np.abs(v.asnumpy()).max()) > 0
    # mismatched explicit shape/dtype refuse instead of corrupting out
    with pytest.raises(MXNetError, match="shape"):
        mx.random.uniform(shape=(3,), out=w)


def test_module_level_binary_and_linspace():
    """Reference nd module-level functions added round 5: power, modulo,
    logical_and/or/xor (array/array, array/scalar, scalar/array) and
    linspace (ref: python/mxnet/ndarray/ndarray.py)."""
    import numpy as np

    a = mx.nd.array(np.array([[2.0, 3.0]], "f4"))
    b = mx.nd.array(np.array([[3.0, 2.0]], "f4"))
    np.testing.assert_allclose(mx.nd.power(a, b).asnumpy(), [[8.0, 9.0]])
    np.testing.assert_allclose(mx.nd.power(a, 2).asnumpy(), [[4.0, 9.0]])
    # scalar LHS of a non-commutative op must NOT operand-swap
    np.testing.assert_allclose(mx.nd.power(2, a).asnumpy(), [[4.0, 8.0]])
    np.testing.assert_allclose(mx.nd.modulo(a, 2).asnumpy(), [[0.0, 1.0]])
    np.testing.assert_allclose(mx.nd.modulo(7, a).asnumpy(), [[1.0, 1.0]])
    t = mx.nd.array(np.array([1.0, 0.0], "f4"))
    f = mx.nd.array(np.array([1.0, 1.0], "f4"))
    np.testing.assert_allclose(mx.nd.logical_and(t, f).asnumpy(), [1, 0])
    np.testing.assert_allclose(mx.nd.logical_or(t, 0).asnumpy(), [1, 0])
    np.testing.assert_allclose(mx.nd.logical_xor(t, f).asnumpy(), [0, 1])
    ls = mx.nd.linspace(0, 1, 5)
    np.testing.assert_allclose(ls.asnumpy(), [0, 0.25, 0.5, 0.75, 1.0])
    ls2 = mx.nd.linspace(0, 1, 4, endpoint=False)
    np.testing.assert_allclose(ls2.asnumpy(), [0, 0.25, 0.5, 0.75])
