"""INT8 quantization: real int8 kernels + calibration + quantize_model
(mxnet_tpu/contrib/quantization.py, ops/quantization.py; ref:
src/operator/quantization/**, python/mxnet/contrib/quantization.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.contrib.quantization import (_get_optimal_threshold,
                                            quantize_model)


def _qdq(x, absmax):
    q = np.clip(np.round(x * (127.0 / absmax)), -127, 127)
    return q * (absmax / 127.0)


def test_quantized_fc_matches_fp32_within_quant_error():
    rng = np.random.RandomState(0)
    x = rng.randn(4, 8).astype(np.float32)
    w = rng.randn(16, 8).astype(np.float32)
    ax, aw = float(np.abs(x).max()), float(np.abs(w).max())
    xq = nd.array(np.clip(np.round(x * 127 / ax), -127, 127).astype(np.int8))
    wq = nd.array(np.clip(np.round(w * 127 / aw), -127, 127).astype(np.int8))
    y32, omin, omax = nd.quantized_fully_connected(
        xq, wq, nd.array([-ax]), nd.array([ax]),
        nd.array([-aw]), nd.array([aw]), num_hidden=16)
    assert y32.dtype == np.int32
    y = nd.dequantize(y32, omin, omax).asnumpy()
    ref = _qdq(x, ax) @ _qdq(w, aw).T
    np.testing.assert_allclose(y, ref, rtol=1e-5, atol=1e-4)


def test_quantized_conv_matches_fp32_within_quant_error():
    rng = np.random.RandomState(1)
    x = rng.randn(2, 3, 8, 8).astype(np.float32)
    w = rng.randn(4, 3, 3, 3).astype(np.float32)
    ax, aw = float(np.abs(x).max()), float(np.abs(w).max())
    xq = nd.array(np.clip(np.round(x * 127 / ax), -127, 127).astype(np.int8))
    wq = nd.array(np.clip(np.round(w * 127 / aw), -127, 127).astype(np.int8))
    y32, omin, omax = nd.quantized_conv(
        xq, wq, nd.array([-ax]), nd.array([ax]),
        nd.array([-aw]), nd.array([aw]),
        kernel=(3, 3), num_filter=4, pad=(1, 1))
    y = nd.dequantize(y32, omin, omax).asnumpy()
    ref = mx.nd.Convolution(nd.array(_qdq(x, ax).astype(np.float32)),
                            nd.array(_qdq(w, aw).astype(np.float32)),
                            kernel=(3, 3), num_filter=4, pad=(1, 1),
                            no_bias=True).asnumpy()
    np.testing.assert_allclose(y, ref, rtol=1e-4, atol=1e-3)


def test_quantized_pooling_int8():
    rng = np.random.RandomState(2)
    x8 = rng.randint(-127, 128, (1, 2, 4, 4)).astype(np.int8)
    out, lo, hi = nd.quantized_pooling(
        nd.array(x8), nd.array([-1.0]), nd.array([1.0]),
        kernel=(2, 2), stride=(2, 2), pool_type="max")
    assert out.dtype == np.int8
    ref = x8.reshape(1, 2, 2, 2, 2, 2).max(axis=(3, 5))
    np.testing.assert_array_equal(out.asnumpy(), ref)


def test_optimal_threshold_clips_outliers():
    rng = np.random.RandomState(3)
    vals = np.concatenate([rng.randn(100_000).astype(np.float32),
                           np.array([100.0], np.float32)])  # one outlier
    th = _get_optimal_threshold(vals)
    assert 0 < th < 50.0  # outlier clipped, bulk preserved
    assert th > 2.0  # but not clipping the gaussian bulk


def _toy_convnet():
    data = mx.sym.var("data")
    c1 = mx.sym.Convolution(data, kernel=(3, 3), num_filter=8,
                            pad=(1, 1), name="conv1")
    a1 = mx.sym.Activation(c1, act_type="relu", name="relu1")
    p1 = mx.sym.Pooling(a1, kernel=(2, 2), stride=(2, 2),
                        pool_type="max", name="pool1")
    f1 = mx.sym.FullyConnected(p1, num_hidden=10, name="fc1")
    return f1


def _init_params(sym, data_shape):
    rng = np.random.RandomState(4)
    args, _, _ = sym.infer_shape(data=data_shape)
    arg_params = {}
    for name, shp in zip(sym.list_arguments(), args):
        if name == "data":
            continue
        arg_params[name] = nd.array(
            (rng.randn(*shp) * 0.1).astype(np.float32))
    return arg_params


@pytest.mark.parametrize("calib_mode", ["none", "naive", "entropy"])
def test_quantize_model_end_to_end(calib_mode):
    data_shape = (4, 3, 8, 8)
    sym = _toy_convnet()
    arg_params = _init_params(sym, data_shape)
    rng = np.random.RandomState(5)
    calib = [nd.array(rng.randn(*data_shape).astype(np.float32))
             for _ in range(3)]

    qsym, qargs, qaux = quantize_model(
        sym, arg_params, {}, calib_mode=calib_mode,
        calib_data=None if calib_mode == "none" else calib,
        quantized_dtype="int8")
    assert "conv1_weight_quantized" in qargs
    assert "fc1_weight_quantized" in qargs
    assert "conv1_weight" not in qargs
    assert qargs["conv1_weight_quantized"].dtype == np.int8
    # biases stay fp32
    assert qargs["conv1_bias"].dtype == np.float32

    x = nd.array(rng.randn(*data_shape).astype(np.float32))
    ref = sym.bind(mx.cpu(), dict(arg_params, data=x),
                   grad_req="null").forward()[0].asnumpy()
    out = qsym.bind(mx.cpu(), dict(qargs, data=x),
                    grad_req="null").forward()[0].asnumpy()
    # int8 model tracks the fp32 model closely on in-distribution data
    denom = np.abs(ref).max() or 1.0
    rel = np.abs(out - ref).max() / denom
    assert rel < 0.12, (calib_mode, rel)
    corr = np.corrcoef(out.ravel(), ref.ravel())[0, 1]
    assert corr > 0.99, (calib_mode, corr)


def test_quantize_model_excluded_layers_stay_fp32():
    data_shape = (2, 3, 8, 8)
    sym = _toy_convnet()
    arg_params = _init_params(sym, data_shape)
    qsym, qargs, _ = quantize_model(
        sym, arg_params, {}, calib_mode="none",
        excluded_sym_names=("conv1",))
    assert "conv1_weight" in qargs  # untouched
    assert "conv1_weight_quantized" not in qargs
    assert "fc1_weight_quantized" in qargs
    rng = np.random.RandomState(6)
    x = nd.array(rng.randn(*data_shape).astype(np.float32))
    out = qsym.bind(mx.cpu(), dict(qargs, data=x),
                    grad_req="null").forward()[0]
    assert np.isfinite(out.asnumpy()).all()


def test_quantize_model_requires_targets_and_valid_mode():
    data = mx.sym.var("data")
    s = mx.sym.Activation(data, act_type="relu", name="r")
    with pytest.raises(mx.MXNetError, match="no quantizable"):
        quantize_model(s, {}, {}, calib_mode="none")
    sym = _toy_convnet()
    with pytest.raises(mx.MXNetError, match="calib_mode"):
        quantize_model(sym, {}, {}, calib_mode="bogus")
    with pytest.raises(mx.MXNetError, match="needs calib_data"):
        quantize_model(sym, {}, {}, calib_mode="naive")


def test_num_calib_examples_smaller_than_batch_still_calibrates():
    data_shape = (4, 3, 8, 8)
    sym = _toy_convnet()
    arg_params = _init_params(sym, data_shape)
    rng = np.random.RandomState(7)
    calib = [nd.array(rng.randn(*data_shape).astype(np.float32))
             for _ in range(4)]
    qsym, qargs, _ = quantize_model(
        sym, arg_params, {}, calib_mode="naive", calib_data=calib,
        num_calib_examples=2)  # < first batch of 4: must still run
    assert "conv1_weight_quantized" in qargs


def test_tied_weight_shared_by_two_layers():
    """A weight var consumed by TWO quantizable layers and by a non-target
    op: quantized once, fp32 original kept for the non-target consumer."""
    data = mx.sym.var("data")
    w = mx.sym.var("w")
    f1 = mx.sym.FullyConnected(data, weight=w, num_hidden=8,
                               no_bias=True, name="fc1")
    f2 = mx.sym.FullyConnected(f1, weight=w, num_hidden=8,
                               no_bias=True, name="fc2")
    # a non-target consumer of the same weight var
    reg = mx.sym.sum(w * w, name="l2")
    out = mx.sym.Group([f2, reg])
    rng = np.random.RandomState(8)
    arg_params = {"w": nd.array(rng.randn(8, 8).astype(np.float32) * 0.3)}
    qsym, qargs, _ = quantize_model(out, arg_params, {}, calib_mode="none")
    assert "w_quantized" in qargs
    assert "w" in qargs  # kept: the l2 term still reads fp32 w
    x = nd.array(rng.randn(2, 8).astype(np.float32))
    res = qsym.bind(mx.cpu(), dict(qargs, data=x),
                    grad_req="null").forward()
    ref_w = arg_params["w"].asnumpy()
    np.testing.assert_allclose(res[1].asnumpy(), (ref_w * ref_w).sum(),
                               rtol=1e-5)


def test_quantized_pooling_full_convention_matches_fp32_shape():
    rng = np.random.RandomState(9)
    x = rng.randn(1, 2, 7, 7).astype(np.float32)
    x8 = np.clip(np.round(x * 63), -127, 127).astype(np.int8)
    fp = mx.nd.Pooling(nd.array(x), kernel=(3, 3), stride=(2, 2),
                       pool_type="max", pooling_convention="full")
    q, _, _ = nd.quantized_pooling(
        nd.array(x8), nd.array([-2.0]), nd.array([2.0]),
        kernel=(3, 3), stride=(2, 2), pool_type="max",
        pooling_convention="full")
    assert q.shape == fp.shape  # ceil-mode shapes agree with fp32 path
    with pytest.raises(mx.MXNetError, match="kernel must have"):
        nd.quantized_pooling(nd.array(x8), nd.array([-2.0]),
                             nd.array([2.0]), pool_type="max")
