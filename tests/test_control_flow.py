"""Control-flow ops + spatial transformer family + UpSampling
(mxnet_tpu/contrib/control_flow.py, ops/nn.py; ref:
src/operator/control_flow.cc, spatial_transformer-inl.h,
upsampling-inl.h)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.contrib import ndarray as C

XS = np.arange(12, dtype=np.float32).reshape(4, 3)


def _body(x, s):
    s2 = s + x
    return s2, s2


def test_foreach_eager_scan():
    outs, final = C.foreach(_body, nd.array(XS), nd.zeros((3,)))
    np.testing.assert_allclose(outs.asnumpy(), np.cumsum(XS, axis=0))
    np.testing.assert_allclose(final.asnumpy(), XS.sum(0))


def test_foreach_multiple_data_and_states():
    d2 = nd.array(XS * 2)
    outs, states = C.foreach(
        lambda xs, ss: ((xs[0] + xs[1], xs[0]), (ss[0] + xs[1], ss[1])),
        [nd.array(XS), d2], [nd.zeros((3,)), nd.ones((3,))])
    np.testing.assert_allclose(outs[0].asnumpy(), XS * 3)
    np.testing.assert_allclose(states[0].asnumpy(), (XS * 2).sum(0))
    np.testing.assert_allclose(states[1].asnumpy(), np.ones(3))


def test_foreach_gradient_through_tape():
    w = nd.ones((3,))
    w.attach_grad()
    with mx.autograd.record():
        o, _ = C.foreach(lambda x, s: (s + x * w, s + x * w),
                         nd.array(XS), nd.zeros((3,)))
        loss = o.sum()
    loss.backward()
    expect = (XS * np.arange(4, 0, -1)[:, None]).sum(0)
    np.testing.assert_allclose(w.grad.asnumpy(), expect, rtol=1e-5)


def test_foreach_traced_composes_into_jit():
    def fn(dv, sv):
        o, f = C.foreach(_body, nd.NDArray(dv), nd.NDArray(sv))
        return o.data, f.data

    o, f = jax.jit(fn)(jnp.asarray(XS), jnp.zeros((3,)))
    np.testing.assert_allclose(np.asarray(o), np.cumsum(XS, axis=0))


def test_while_loop_eager_and_traced():
    i0 = nd.array(np.array([0.0], np.float32))
    outs, fin = C.while_loop(lambda i: i < 3, lambda i: (i * 2, i + 1),
                             [i0], max_iterations=5)
    np.testing.assert_allclose(fin[0].asnumpy(), [3.0])
    np.testing.assert_allclose(outs.asnumpy().ravel(), [0, 2, 4, 0, 0])

    def fn(iv):
        o, fin = C.while_loop(
            lambda i: i.reshape(()) < 3, lambda i: (i * 2, i + 1),
            [nd.NDArray(iv)], max_iterations=5)
        return o.data, fin[0].data

    o, fv = jax.jit(fn)(jnp.array([0.0]))
    np.testing.assert_allclose(np.asarray(fv), [3.0])
    np.testing.assert_allclose(np.asarray(o).ravel(), [0, 2, 4, 0, 0])
    with pytest.raises(mx.MXNetError, match="max_iterations"):
        C.while_loop(lambda i: i < 3, lambda i: (i, i), [i0])


def test_cond_eager_and_traced():
    r = C.cond(nd.array(np.array([1.0])), lambda: nd.ones((2,)),
               lambda: nd.zeros((2,)))
    np.testing.assert_allclose(r.asnumpy(), [1, 1])

    def fn(p):
        return C.cond(nd.NDArray(p), lambda: nd.ones((2,)),
                      lambda: nd.zeros((2,))).data

    assert np.asarray(jax.jit(fn)(jnp.array(1.0))).tolist() == [1, 1]
    assert np.asarray(jax.jit(fn)(jnp.array(0.0))).tolist() == [0, 0]


# ---------------------------------------------------------------------------
# UpSampling + SpatialTransformer family
# ---------------------------------------------------------------------------

def test_upsampling_nearest_and_bilinear():
    x = nd.array(np.arange(2 * 1 * 4 * 4, np.float32).reshape(2, 1, 4, 4)
                 if False else
                 np.arange(32, dtype=np.float32).reshape(2, 1, 4, 4))
    u = nd.UpSampling(x, scale=2, sample_type="nearest")
    assert u.shape == (2, 1, 8, 8)
    np.testing.assert_allclose(
        u.asnumpy()[:, :, ::2, ::2], x.asnumpy())
    b = nd.UpSampling(x, scale=2, sample_type="bilinear")
    assert b.shape == (2, 1, 8, 8)
    # bilinear preserves mean
    np.testing.assert_allclose(b.asnumpy().mean(), x.asnumpy().mean(),
                               rtol=0.05)


def test_upsampling_multi_input_concat():
    a = nd.ones((1, 2, 4, 4))
    b = nd.ones((1, 3, 2, 2)) * 2
    out = nd.UpSampling(a, b, scale=2, sample_type="nearest", num_args=2)
    assert out.shape == (1, 5, 8, 8)
    np.testing.assert_allclose(out.asnumpy()[:, 2:], 2 * np.ones((1, 3, 8, 8)))


def test_spatial_transformer_identity_and_shift():
    x = nd.array(np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4))
    ident = nd.array(np.array([[1, 0, 0, 0, 1, 0]], np.float32))
    out = nd.SpatialTransformer(x, ident, target_shape=(4, 4))
    np.testing.assert_allclose(out.asnumpy(), x.asnumpy(), atol=1e-5)
    # grid generator + sampler compose to the same thing
    g = nd.GridGenerator(ident, transform_type="affine",
                         target_shape=(4, 4))
    np.testing.assert_allclose(nd.BilinearSampler(x, g).asnumpy(),
                               x.asnumpy(), atol=1e-5)


def test_bilinear_sampler_zero_padding_outside():
    x = nd.ones((1, 1, 4, 4))
    # shift far right: everything samples outside -> zeros
    theta = nd.array(np.array([[1, 0, 10.0, 0, 1, 0]], np.float32))
    out = nd.SpatialTransformer(x, theta, target_shape=(4, 4))
    np.testing.assert_allclose(out.asnumpy(), np.zeros((1, 1, 4, 4)))


def test_grid_generator_warp():
    x = nd.array(np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4))
    zero_flow = nd.zeros((1, 2, 4, 4))
    g = nd.GridGenerator(zero_flow, transform_type="warp")
    np.testing.assert_allclose(nd.BilinearSampler(x, g).asnumpy(),
                               x.asnumpy(), atol=1e-5)


def test_bilinear_sampler_gradient():
    from mxnet_tpu.test_utils import check_numeric_gradient

    x = nd.array(np.random.RandomState(0).randn(1, 1, 3, 3)
                 .astype(np.float32))
    theta = nd.array(np.array([[1, 0, 0.1, 0, 1, -0.1]], np.float32))
    theta.attach_grad()
    x.attach_grad()
    with mx.autograd.record():
        out = nd.SpatialTransformer(x, theta, target_shape=(3, 3))
        loss = (out * out).sum()
    loss.backward()
    assert np.isfinite(theta.grad.asnumpy()).all()
    assert np.abs(theta.grad.asnumpy()).sum() > 0
    assert np.isfinite(x.grad.asnumpy()).all()


def test_sequence_camelcase_aliases():
    x = nd.array(np.ones((3, 2), np.float32))
    sl = nd.array(np.array([1, 2], np.float32))
    m = nd.SequenceMask(x, sl, use_sequence_length=True)
    np.testing.assert_allclose(m.asnumpy(),
                               [[1, 1], [0, 1], [0, 0]])
    last = nd.SequenceLast(x, sl, use_sequence_length=True)
    assert last.shape == (2,)
    rev = nd.SequenceReverse(x, sl, use_sequence_length=True)
    assert rev.shape == x.shape


def test_foreach_in_hybridized_block_with_dropout():
    """The hardest composition: a keyed op (Dropout) inside foreach
    inside a hybridized block — body PRNG draws must stay scan-local
    (one key folded per iteration), not contaminate the outer trace."""
    from mxnet_tpu.gluon import nn, HybridBlock

    class ScanRNN(HybridBlock):
        def __init__(self):
            super().__init__()
            self.cell = nn.Dense(8, in_units=8 + 4, activation="relu")
            self.drop = nn.Dropout(0.3)
            self.out = nn.Dense(2, in_units=8)

        def forward(self, x):
            init = nd.zeros((x.shape[1], 8), ctx=x.ctx)

            def step(xt, h):
                h2 = self.drop(self.cell(nd.concat(h, xt, dim=1)))
                return h2, h2

            _, final = C.foreach(step, x, init)
            return self.out(final)

    X = np.random.RandomState(0).randn(5, 4, 4).astype("f4")
    net = ScanRNN()
    net.initialize(mx.initializer.Xavier())
    net.hybridize()
    out = net(nd.array(X))
    assert out.shape == (4, 2)
    assert np.isfinite(out.asnumpy()).all()
    out2 = net(nd.array(X))  # cached executable path
    assert out2.shape == (4, 2)
    # gradient through the eager (tape) path with the same net
    net2 = ScanRNN()
    net2.initialize(mx.initializer.Xavier())
    with mx.autograd.record():
        loss = (net2(nd.array(X)) ** 2).sum()
    loss.backward()
    g = net2.cell.weight.grad().asnumpy()
    assert np.isfinite(g).all()


def test_while_loop_false_on_entry_consistent():
    """cond false on entry: eager and traced agree (zero-filled padded
    buffers + unchanged loop vars), no eager-only exception."""
    i0 = nd.array(np.array([5.0], np.float32))
    outs, fin = C.while_loop(lambda i: i < 0, lambda i: (i * 2, i + 1),
                             [i0], max_iterations=4)
    np.testing.assert_allclose(outs.asnumpy(), np.zeros((4, 1)))
    np.testing.assert_allclose(fin[0].asnumpy(), [5.0])

    def fn(iv):
        o, fin = C.while_loop(
            lambda i: i.reshape(()) < 0, lambda i: (i * 2, i + 1),
            [nd.NDArray(iv)], max_iterations=4)
        return o.data, fin[0].data

    o, fv = jax.jit(fn)(jnp.array([5.0]))
    np.testing.assert_allclose(np.asarray(o), np.zeros((4, 1)))
    np.testing.assert_allclose(np.asarray(fv), [5.0])


def test_while_loop_plain_bool_cond_traced():
    """cond_fn returning a raw jnp value (not NDArray) works when traced
    — same coercion as the eager path."""
    def fn(iv):
        o, fin = C.while_loop(
            lambda i: i.data.reshape(()) < 3, lambda i: (i * 2, i + 1),
            [nd.NDArray(iv)], max_iterations=5)
        return fin[0].data

    np.testing.assert_allclose(
        np.asarray(jax.jit(fn)(jnp.array([0.0]))), [3.0])
