"""Gluon utilities (ref: python/mxnet/gluon/utils.py): split_and_load,
split_data, clip_global_norm, check_sha1, download stub."""
from __future__ import annotations

import hashlib
import math
from typing import List, Optional, Sequence

import numpy as np

from ..base import MXNetError
from ..context import Context
from ..ndarray.ndarray import NDArray, array as nd_array

__all__ = ["split_data", "split_and_load", "clip_global_norm", "check_sha1",
           "download"]


def split_data(data: NDArray, num_slice: int, batch_axis: int = 0,
               even_split: bool = True) -> List[NDArray]:
    """Slice a batch along batch_axis into num_slice chunks
    (ref: utils.py::split_data)."""
    size = data.shape[batch_axis]
    if even_split and size % num_slice != 0:
        raise MXNetError(
            f"data with shape {data.shape} cannot be evenly split into "
            f"{num_slice} slices along axis {batch_axis}")
    step = size // num_slice
    if not even_split and size % num_slice != 0:
        slices = []
        for i in range(num_slice):
            begin = int(round(i * size / num_slice))
            end = int(round((i + 1) * size / num_slice))
            idx = [slice(None)] * data.ndim
            idx[batch_axis] = slice(begin, end)
            slices.append(data[tuple(idx)])
        return slices
    out = []
    for i in range(num_slice):
        idx = [slice(None)] * data.ndim
        idx[batch_axis] = slice(i * step, (i + 1) * step)
        out.append(data[tuple(idx)])
    return out


def split_and_load(data, ctx_list: Sequence[Context], batch_axis: int = 0,
                   even_split: bool = True) -> List[NDArray]:
    """Slice and scatter across contexts (ref: utils.py::split_and_load) —
    the Gluon data-parallel entry point."""
    if not isinstance(data, NDArray):
        data = nd_array(data)
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [s.as_in_context(c) for s, c in zip(slices, ctx_list)]


def clip_global_norm(arrays: Sequence[NDArray], max_norm: float,
                     check_isfinite: bool = True) -> float:
    """Rescale grads so the global L2 norm <= max_norm
    (ref: utils.py::clip_global_norm)."""
    if not arrays:
        raise MXNetError("no arrays given")
    total = 0.0
    for a in arrays:
        n = a.norm().asscalar()
        total += float(n) ** 2
    total = math.sqrt(total)
    if check_isfinite and not math.isfinite(total):
        raise MXNetError(f"global norm is not finite ({total})")
    scale = max_norm / (total + 1e-8)
    if scale < 1.0:
        for a in arrays:
            a._data = a.data * scale
    return total


def check_sha1(filename: str, sha1_hash: str) -> bool:
    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        while True:
            data = f.read(1048576)
            if not data:
                break
            sha1.update(data)
    return sha1.hexdigest() == sha1_hash


def download(url, path=None, overwrite=False, sha1_hash=None, retries=5,
             verify_ssl=True):
    """Zero-egress environment: downloads are unavailable; datasets must be
    staged locally (ref: utils.py::download)."""
    raise MXNetError(
        "download() is unavailable in this offline build; place the file "
        f"locally and pass its path (requested: {url})")
