"""Sparse NDArrays: RowSparseNDArray and CSRNDArray.

TPU-native counterpart of the reference's sparse frontend + storage types
(ref: python/mxnet/ndarray/sparse.py — BaseSparseNDArray/RowSparseNDArray/
CSRNDArray; include/mxnet/ndarray.h kRowSparseStorage/kCSRStorage;
src/operator/tensor/cast_storage-inl.h, dot-inl.h, sparse_retain-inl.h).

Design (TPU-first, not a port): XLA has no sparse storage — the MXU wants
dense tiles — so a sparse array here is a **dense HBM backing plus explicit
aux index arrays** kept in sync:

  * the dense backing means every dense op/kernel keeps working and
    conversion to/from 'default' storage is free of surprises;
  * the aux arrays (`indices` for row_sparse; `indices`+`indptr` for csr)
    carry the reference's *semantics* — which rows/positions are explicitly
    stored — which is what retain/row_sparse_pull/lazy optimizer updates
    and serialization actually need;
  * hot sparse math (dot(csr, dense), sparse elemwise) lowers to gathers/
    segment-sums on the dense backing — XLA-friendly static shapes, nnz
    fixed per instance.

An explicitly stored row may contain zeros, exactly like the reference:
`indices` is authoritative, not derived from the values.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..base import MXNetError
from ..context import Context, cpu, current_context
from .ndarray import NDArray, _resolve_dtype, array as _dense_array

__all__ = ["BaseSparseNDArray", "RowSparseNDArray", "CSRNDArray",
           "csr_matrix", "row_sparse_array", "zeros", "empty", "array",
           "cast_storage", "retain", "dot", "add", "subtract", "multiply",
           "divide"]

_STORAGE_TYPE_STR_TO_ID = {"undefined": -1, "default": 0, "row_sparse": 1,
                           "csr": 2}
_STORAGE_TYPE_ID_TO_STR = {v: k for k, v in _STORAGE_TYPE_STR_TO_ID.items()}


class BaseSparseNDArray(NDArray):
    """Common base: dense jax backing + explicit aux index arrays."""

    __slots__ = ("_aux",)

    def __init__(self, dense, aux, ctx: Optional[Context] = None, dtype=None):
        super().__init__(dense, ctx=ctx, dtype=dtype)
        self._aux = aux  # dict of name -> jax int32/int64 array

    # dense views --------------------------------------------------------
    def tostype(self, stype: str):
        if stype == self.stype:
            return self
        if stype == "default":
            return NDArray(self._data, ctx=self._ctx)
        return cast_storage(self, stype)

    def todense(self) -> NDArray:
        return self.tostype("default")

    def asnumpy(self):
        return np.asarray(jax.device_get(self._data))

    def copy(self):
        return type(self)(jnp.copy(self._data),
                          {k: jnp.copy(v) for k, v in self._aux.items()},
                          ctx=self._ctx)

    def astype(self, dtype, copy=True):
        dt = _resolve_dtype(dtype)
        if not copy and self._data.dtype == dt:
            return self
        return type(self)(self._data.astype(dt), dict(self._aux),
                          ctx=self._ctx)

    def as_in_context(self, ctx: Context):
        if ctx == self._ctx:
            return self
        dev = ctx.jax_device
        return type(self)(jax.device_put(self._data, dev),
                          {k: jax.device_put(v, dev)
                           for k, v in self._aux.items()}, ctx=ctx)

    def copyto(self, other):
        if isinstance(other, Context):
            return self.as_in_context(other)
        if isinstance(other, BaseSparseNDArray):
            other._data = jax.device_put(self._data, other.ctx.jax_device)
            other._aux = {k: jax.device_put(v, other.ctx.jax_device)
                          for k, v in self._aux.items()}
            return other
        # sparse -> dense copy densifies (ref: CopyFromTo cross-stype)
        other._data = jax.device_put(self._data, other.ctx.jax_device)
        return other

    def __repr__(self):
        dims = "x".join(map(str, self.shape))
        return (f"\n<{type(self).__name__} {dims} @{self._ctx}>")

    def _deny(self, what):
        raise MXNetError(f"{what} is not supported for {self.stype} storage; "
                         f"call .tostype('default') first")

    def __iadd__(self, o):
        self._deny("inplace arithmetic")

    def __setitem__(self, key, value):
        if key is Ellipsis or (isinstance(key, slice) and key == slice(None)):
            if isinstance(value, BaseSparseNDArray):
                value.copyto(self)
                return
            if isinstance(value, NDArray):
                fresh = cast_storage(value, self.stype)
            else:
                fresh = cast_storage(_dense_array(value, ctx=self._ctx),
                                     self.stype)
            fresh.copyto(self)
            return
        self._deny("sliced assignment")


class RowSparseNDArray(BaseSparseNDArray):
    """ref: RowSparseNDArray — values for a subset of rows.

    aux: `indices` (sorted int64 row ids, shape (num_stored,)).
    `.data` is the (num_stored, *row_shape) value block.
    """

    __slots__ = ()

    @property
    def stype(self):
        return "row_sparse"

    @property
    def indices(self) -> NDArray:
        return NDArray(self._aux["indices"], ctx=self._ctx)

    @property
    def data(self) -> NDArray:
        # the stored-rows value block, gathered from the dense backing
        return NDArray(jnp.take(self._data, self._aux["indices"], axis=0),
                       ctx=self._ctx)

    @property
    def _values_jax(self):
        return jnp.take(self._data, self._aux["indices"], axis=0)

    def retain(self, rsp_indices):
        return retain(self, rsp_indices)


class CSRNDArray(BaseSparseNDArray):
    """ref: CSRNDArray — compressed sparse row matrix.

    aux: `indices` (column ids, shape (nnz,)), `indptr` (row pointers,
    shape (rows+1,)).  `.data` is the (nnz,) value vector.
    """

    __slots__ = ()

    @property
    def stype(self):
        return "csr"

    @property
    def indices(self) -> NDArray:
        return NDArray(self._aux["indices"], ctx=self._ctx)

    @property
    def indptr(self) -> NDArray:
        return NDArray(self._aux["indptr"], ctx=self._ctx)

    @property
    def data(self) -> NDArray:
        rows = self._row_ids()
        cols = self._aux["indices"]
        return NDArray(self._data[rows, cols], ctx=self._ctx)

    def _row_ids(self):
        """Per-nnz row id, from indptr (static nnz => static shapes)."""
        indptr = self._aux["indptr"]
        nnz = int(self._aux["indices"].shape[0])
        counts = jnp.diff(indptr)
        return jnp.repeat(jnp.arange(indptr.shape[0] - 1, dtype=jnp.int32),
                          counts, total_repeat_length=nnz)

    def asscipy(self):
        import scipy.sparse as sps

        return sps.csr_matrix(
            (np.asarray(jax.device_get(self.data.data)),
             np.asarray(jax.device_get(self._aux["indices"])),
             np.asarray(jax.device_get(self._aux["indptr"]))),
            shape=self.shape)

    def __getitem__(self, key):
        if isinstance(key, int):
            key = slice(key, key + 1)
        if isinstance(key, slice):
            dense = self._data[key]
            return cast_storage(NDArray(dense, ctx=self._ctx), "csr")
        raise MXNetError("CSRNDArray only supports int/slice row indexing")


# ---------------------------------------------------------------------------
# construction
# ---------------------------------------------------------------------------

def _to_jax_idx(x, dtype=jnp.int32):
    if isinstance(x, NDArray):
        x = x.data
    return jnp.asarray(np.asarray(x), dtype=dtype)


def row_sparse_array(arg1, shape=None, ctx=None, dtype=None):
    """ref: sparse.row_sparse_array — from (data, indices) or dense."""
    ctx = ctx or current_context()
    if isinstance(arg1, tuple) and len(arg1) == 2 and not np.isscalar(arg1[0]):
        values, indices = arg1
        values = np.asarray(values if not isinstance(values, NDArray)
                            else values.asnumpy())
        if dtype is None:
            dtype = "float32" if values.dtype == np.float64 else values.dtype
        indices = np.asarray(indices, np.int64).reshape(-1)
        order = np.argsort(indices)
        indices = indices[order]
        values = values[order]
        if shape is None:
            nrows = int(indices[-1]) + 1 if indices.size else 0
            shape = (nrows,) + tuple(values.shape[1:])
        dense = np.zeros(shape, dtype=np.asarray(values).dtype)
        if indices.size:
            dense[indices] = values
        dev = ctx.jax_device
        return RowSparseNDArray(
            jax.device_put(jnp.asarray(dense, _resolve_dtype(dtype)), dev),
            {"indices": jax.device_put(jnp.asarray(indices), dev)}, ctx=ctx)
    # dense input (ndarray / NDArray / nested lists)
    nd = arg1 if isinstance(arg1, NDArray) else _dense_array(
        arg1, ctx=ctx, dtype=dtype)
    return cast_storage(nd, "row_sparse")


def csr_matrix(arg1, shape=None, ctx=None, dtype=None):
    """ref: sparse.csr_matrix — from (data, indices, indptr),
    (data, (row, col)), a scipy.sparse matrix, or dense."""
    ctx = ctx or current_context()
    try:
        import scipy.sparse as sps
        if sps.issparse(arg1):
            csr = arg1.tocsr()
            return csr_matrix((csr.data, csr.indices, csr.indptr),
                              shape=csr.shape, ctx=ctx, dtype=dtype)
    except ImportError:
        pass
    if isinstance(arg1, tuple) and len(arg1) == 3:
        values, indices, indptr = arg1
        values = np.asarray(values if not isinstance(values, NDArray)
                            else values.asnumpy())
        if dtype is None:
            dtype = "float32" if values.dtype == np.float64 else values.dtype
        indices = np.asarray(indices, np.int64).reshape(-1)
        indptr = np.asarray(indptr, np.int64).reshape(-1)
        if shape is None:
            ncols = int(indices.max()) + 1 if indices.size else 0
            shape = (len(indptr) - 1, ncols)
        dense = np.zeros(shape, dtype=values.dtype)
        rows = np.repeat(np.arange(len(indptr) - 1), np.diff(indptr))
        dense[rows, indices] = values
        dev = ctx.jax_device
        return CSRNDArray(
            jax.device_put(jnp.asarray(dense, _resolve_dtype(dtype)), dev),
            {"indices": jax.device_put(jnp.asarray(indices), dev),
             "indptr": jax.device_put(jnp.asarray(indptr), dev)}, ctx=ctx)
    if isinstance(arg1, tuple) and len(arg1) == 2 \
            and isinstance(arg1[1], tuple):
        values, (row, col) = arg1
        import scipy.sparse as sps
        m = sps.coo_matrix((np.asarray(values),
                            (np.asarray(row), np.asarray(col))),
                           shape=shape).tocsr()
        return csr_matrix(m, shape=shape, ctx=ctx, dtype=dtype)
    nd = arg1 if isinstance(arg1, NDArray) else _dense_array(
        arg1, ctx=ctx, dtype=dtype)
    return cast_storage(nd, "csr")


def zeros(stype, shape, ctx=None, dtype=None):
    """ref: sparse.zeros — all-zero sparse array (nothing stored)."""
    ctx = ctx or current_context()
    if isinstance(shape, int):
        shape = (shape,)
    dev = ctx.jax_device
    dense = jax.device_put(jnp.zeros(shape, _resolve_dtype(dtype)), dev)
    if stype == "row_sparse":
        return RowSparseNDArray(
            dense, {"indices": jax.device_put(jnp.zeros((0,), jnp.int32),
                                              dev)}, ctx=ctx)
    if stype == "csr":
        return CSRNDArray(
            dense,
            {"indices": jax.device_put(jnp.zeros((0,), jnp.int32), dev),
             "indptr": jax.device_put(jnp.zeros((shape[0] + 1,), jnp.int32),
                                      dev)}, ctx=ctx)
    if stype == "default":
        return NDArray(dense, ctx=ctx)
    raise MXNetError(f"unknown storage type {stype!r}")


def empty(stype, shape, ctx=None, dtype=None):
    return zeros(stype, shape, ctx=ctx, dtype=dtype)


def array(source, ctx=None, dtype=None):
    """ref: sparse.array — build from another sparse array (incl. scipy)."""
    try:
        import scipy.sparse as sps
        if sps.issparse(source):
            return csr_matrix(source, ctx=ctx, dtype=dtype)
    except ImportError:
        pass
    if isinstance(source, BaseSparseNDArray):
        out = source.copy()
        if dtype is not None:
            out = out.astype(dtype)
        return out.as_in_context(ctx) if ctx is not None else out
    raise MXNetError("sparse.array expects a sparse input; use nd.array for "
                     "dense sources")


# ---------------------------------------------------------------------------
# storage casts / structural ops (ref: cast_storage-inl.h, sparse_retain)
# ---------------------------------------------------------------------------

def cast_storage(arr: NDArray, stype: str):
    """ref: nd.cast_storage — convert between storage types.

    Structure discovery (nonzero scan) happens host-side: storage casts are
    an eager/etl operation, never inside a jitted step."""
    if stype == arr.stype:
        return arr
    ctx = arr.ctx
    dev = ctx.jax_device
    dense_np = np.asarray(jax.device_get(arr._data))
    if stype == "default":
        return NDArray(arr._data, ctx=ctx)
    if stype == "row_sparse":
        if dense_np.ndim < 1:
            raise MXNetError("row_sparse needs ndim >= 1")
        nz_rows = np.flatnonzero(
            dense_np.reshape(dense_np.shape[0], -1).any(axis=1))
        return RowSparseNDArray(
            arr._data, {"indices": jax.device_put(
                jnp.asarray(nz_rows, jnp.int32), dev)}, ctx=ctx)
    if stype == "csr":
        if dense_np.ndim != 2:
            raise MXNetError("csr storage requires a 2-D array")
        import scipy.sparse as sps
        if dense_np.dtype.name not in ("float32", "float64", "int32",
                                       "int64", "int8", "uint8"):
            # scipy rejects ml_dtypes (bfloat16/float16); only the nonzero
            # STRUCTURE is needed, so discover it on a float32 view
            m = sps.csr_matrix(dense_np.astype(np.float32))
        else:
            m = sps.csr_matrix(dense_np)
        return CSRNDArray(
            arr._data,
            {"indices": jax.device_put(jnp.asarray(m.indices, jnp.int32),
                                       dev),
             "indptr": jax.device_put(jnp.asarray(m.indptr, jnp.int32),
                                      dev)}, ctx=ctx)
    raise MXNetError(f"unknown storage type {stype!r}")


def retain(rsp: RowSparseNDArray, indices):
    """ref: sparse_retain — keep only the requested rows of a row_sparse."""
    if not isinstance(rsp, RowSparseNDArray):
        raise MXNetError("retain expects a RowSparseNDArray")
    keep = _to_jax_idx(indices)
    mask = jnp.zeros((rsp.shape[0],), bool).at[keep].set(True)
    dense = jnp.where(mask.reshape((-1,) + (1,) * (rsp.ndim - 1)),
                      rsp._data, 0)
    stored = rsp._aux["indices"]
    stored_mask = jnp.zeros((rsp.shape[0],), bool).at[stored].set(True)
    new_idx = keep[stored_mask[keep]] if keep.size else keep
    new_idx = jnp.sort(new_idx)
    return RowSparseNDArray(dense, {"indices": new_idx}, ctx=rsp.ctx)


# ---------------------------------------------------------------------------
# math (ref: dot-inl.h FComputeEx, elemwise sparse kernels)
# ---------------------------------------------------------------------------

def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """ref: nd.sparse.dot — dot(csr, dense) / dot(csr.T, dense).

    Lowered to a dense matmul on the MXU: the dense backing makes this one
    XLA HLO with no scatter/gather chains, the right call on TPU where
    structured-sparse speedups don't exist."""
    if isinstance(lhs, CSRNDArray):
        a = lhs._data
    elif isinstance(lhs, NDArray):
        a = lhs.data
    else:
        raise MXNetError("sparse.dot lhs must be NDArray/CSRNDArray")
    b = rhs._data if isinstance(rhs, BaseSparseNDArray) else rhs.data
    if transpose_a:
        a = a.T
    if transpose_b:
        b = b.T
    return NDArray(jnp.matmul(a, b), ctx=lhs.ctx)


def _ew(op, lhs, rhs):
    lstype = getattr(lhs, "stype", "default")
    rstype = getattr(rhs, "stype", "default")
    ld = lhs._data if isinstance(lhs, NDArray) else jnp.asarray(lhs)
    rd = rhs._data if isinstance(rhs, NDArray) else jnp.asarray(rhs)
    out = op(ld, rd)
    ctx = lhs.ctx if isinstance(lhs, NDArray) else rhs.ctx
    # same-stype elemwise keeps the stype, like the reference's FComputeEx
    if lstype == rstype == "row_sparse" and out.shape == lhs.shape:
        merged = jnp.sort(jnp.unique(
            jnp.concatenate([lhs._aux["indices"], rhs._aux["indices"]])))
        return RowSparseNDArray(out, {"indices": merged}, ctx=ctx)
    if lstype == rstype == "csr" and out.shape == lhs.shape:
        return cast_storage(NDArray(out, ctx=ctx), "csr")
    return NDArray(out, ctx=ctx)


def add(lhs, rhs):
    return _ew(jnp.add, lhs, rhs)


def subtract(lhs, rhs):
    return _ew(jnp.subtract, lhs, rhs)


def multiply(lhs, rhs):
    return _ew(jnp.multiply, lhs, rhs)


def divide(lhs, rhs):
    return _ew(jnp.divide, lhs, rhs)


def add_n(*args):
    out = args[0]
    for a in args[1:]:
        out = add(out, a)
    return out
