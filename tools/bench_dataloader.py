"""DataLoader crossover benchmark (VERDICT r3 weak #3): threaded vs
spawn-process workers vs single-threaded, on the two workload classes
that behave oppositely under the GIL:

  * numpy-heavy __getitem__ (decode/augment in C, releases the GIL) —
    the threaded pool's home turf;
  * pure-python __getitem__ (user transforms in python) — threads
    serialize on the GIL; the process pool is the escape hatch.

Writes DATALOADER_BENCH.json and prints one JSON line per case.
Interpret per-host: on a 1-core dev box NO pool can beat single-thread
on CPU-bound work (the numbers there validate the harness and overhead,
not the crossover); on a multi-core host the pure-python workload
crosses over to worker_pool="process" as soon as per-sample python time
dominates the pickling cost.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

import numpy as np  # noqa: E402


class NumpyHeavy:
    """Simulated decode/augment: numpy ops on a 256x256 image (GIL
    released inside numpy)."""

    def __init__(self, n):
        self.n = n
        self.img = np.random.RandomState(0).rand(256, 256, 3) \
            .astype(np.float32)

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        x = self.img * (1.0 + 0.01 * (i % 7))
        x = x[::-1].copy()
        x = (x - x.mean()) / (x.std() + 1e-6)
        return x.astype(np.float32)


class PurePython:
    """User transform in pure python (holds the GIL)."""

    def __init__(self, n, work=20000):
        self.n, self.work = n, work

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        acc = 0
        for k in range(self.work):
            acc = (acc + i * k) % 9973
        return np.array([i, acc], np.float32)


def _run(ds, batch_size, num_workers, worker_pool, transport="shm"):
    from mxnet_tpu.gluon.data import DataLoader

    kw = {}
    if num_workers:
        kw = dict(num_workers=num_workers, worker_pool=worker_pool,
                  worker_transport=transport)
    dl = DataLoader(ds, batch_size=batch_size, **kw)
    list(dl)  # warm (spawn pool startup / thread seeding out of timing)
    t0 = time.perf_counter()
    n = 0
    for b in dl:
        n += b.shape[0] if hasattr(b, "shape") else len(b)
    dt = time.perf_counter() - t0
    return n / dt


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=192)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--out",
                    default=os.path.join(_REPO, "DATALOADER_BENCH.json"))
    args = ap.parse_args()

    results = []
    for wl_name, ds in (("numpy_heavy", NumpyHeavy(args.n)),
                        ("pure_python", PurePython(args.n))):
        cases = [("single", 0, "shm"), ("thread", args.workers, "shm"),
                 ("process", args.workers, "shm"),
                 ("process", args.workers, "pipe")]
        for pool, nw, transport in cases:
            tp = _run(ds, args.batch_size, nw, pool, transport)
            row = {"workload": wl_name, "pool": pool, "workers": nw,
                   "samples_per_s": round(tp, 1)}
            if pool == "process":
                row["transport"] = transport
            results.append(row)
            print(json.dumps(row))

    with open(args.out, "w") as f:
        json.dump({"when": time.strftime("%Y-%m-%d %H:%M:%S"),
                   "cores": os.cpu_count(),
                   "note": "1-core hosts cannot show the parallel "
                           "crossover; see tools/bench_dataloader.py "
                           "docstring and docs/data.md",
                   "results": results}, f, indent=1)
    return 0


if __name__ == "__main__":
    sys.exit(main())
