"""Training callbacks (ref: python/mxnet/callback.py).

`Speedometer` (throughput logging), `do_checkpoint` (epoch-end model
save), `ProgressBar`, `log_train_metric` — consumed by `Module.fit` and
user loops, same as the reference.
"""
from __future__ import annotations

import logging
import math
import sys
import time
from collections import namedtuple

__all__ = ["Speedometer", "ProgressBar", "do_checkpoint",
           "module_checkpoint", "log_train_metric", "BatchEndParam"]

BatchEndParam = namedtuple("BatchEndParam",
                           ["epoch", "nbatch", "eval_metric", "locals"])


class Speedometer:
    """Log samples/sec every `frequent` batches (ref: callback.Speedometer)."""

    def __init__(self, batch_size: int, frequent: int = 50,
                 auto_reset: bool = True):
        self.batch_size = batch_size
        self.frequent = frequent
        self.auto_reset = auto_reset
        self.init = False
        self.tic = 0.0
        self.last_count = 0

    def __call__(self, param: BatchEndParam):
        count = param.nbatch
        if self.last_count > count:
            self.init = False
        self.last_count = count
        if self.init:
            if count % self.frequent == 0:
                speed = self.frequent * self.batch_size / (time.time() - self.tic)
                if param.eval_metric is not None:
                    name_value = param.eval_metric.get_name_value()
                    if self.auto_reset:
                        param.eval_metric.reset()
                    msg = "Epoch[%d] Batch [%d]\tSpeed: %.2f samples/sec\t%s"
                    metrics = "\t".join(f"{n}={v:f}" for n, v in name_value)
                    logging.info(msg, param.epoch, count, speed, metrics)
                else:
                    logging.info("Iter[%d] Batch [%d]\tSpeed: %.2f samples/sec",
                                 param.epoch, count, speed)
                self.tic = time.time()
        else:
            self.init = True
            self.tic = time.time()


class ProgressBar:
    """Text progress bar per batch (ref: callback.ProgressBar)."""

    def __init__(self, total: int, length: int = 80):
        self.total = total
        self.bar_len = length

    def __call__(self, param: BatchEndParam):
        count = param.nbatch
        filled_len = int(round(self.bar_len * count / float(self.total)))
        percents = math.ceil(100.0 * count / float(self.total))
        prog_bar = "=" * filled_len + "-" * (self.bar_len - filled_len)
        sys.stdout.write(f"[{prog_bar}] {percents}%\r")


def do_checkpoint(prefix: str, period: int = 1):
    """Epoch-end callback saving `prefix-symbol.json` +
    `prefix-%04d.params` (ref: callback.do_checkpoint)."""
    from .model import save_checkpoint

    period = int(max(1, period))

    def _callback(iter_no, sym, arg, aux):
        if (iter_no + 1) % period == 0:
            save_checkpoint(prefix, iter_no + 1, sym, arg, aux)

    return _callback


def module_checkpoint(mod, prefix: str, period: int = 1,
                      save_optimizer_states: bool = False):
    """ref: callback.module_checkpoint."""
    period = int(max(1, period))

    def _callback(iter_no, sym=None, arg=None, aux=None):
        if (iter_no + 1) % period == 0:
            mod.save_checkpoint(prefix, iter_no + 1, save_optimizer_states)

    return _callback


def log_train_metric(period: int, auto_reset: bool = False):
    """ref: callback.log_train_metric."""

    def _callback(param: BatchEndParam):
        if param.nbatch % period == 0 and param.eval_metric is not None:
            name_value = param.eval_metric.get_name_value()
            for name, value in name_value:
                logging.info("Iter[%d] Batch[%d] Train-%s=%f",
                             param.epoch, param.nbatch, name, value)
            if auto_reset:
                param.eval_metric.reset()

    return _callback
