"""Compile provenance: *why* did this site compile again?

mxprof (PR 10) counts compiles per step and mxsan (PR 5) flags
recompile storms — but a count is not a cause.  This module turns
every compile-cache miss into a structured *diff against the nearest
prior signature at the same site*: which named component of the
executable's identity changed (avals / statics / donation / device /
program text / env fingerprint / ...).

Call sites name their components on the :class:`CacheKey`
(``cache_key(..., components={"avals": ..., "donation": ...})``); a
miss lands in three places:

  * the per-site history kept here (``history(site)``) — what the
    provenance tests and ``mxtriage`` reports read;
  * ``mx_compile_reason_total{site,component}`` — the operational
    counter a dashboard slices a recompile storm by;
  * the mxprof compile-event stream — the flight recorder's pending
    step record grows a ``compile_reasons`` entry, so a dump shows the
    storm's cause on the exact step it hit.

"Nearest prior" is the retained signature sharing the most component
digests with the new one — a site that alternates between two shapes
is diffed against its own shape-family, not whatever compiled last.
"""
from __future__ import annotations

import threading
from collections import deque
from typing import Dict, List, Optional

from .. import instruments as _ins
from .. import tracing as _tracing

__all__ = ["record_miss", "history", "clear"]

# prior signatures retained per site; small — provenance needs the
# recent shape families at a site, not its lifetime history
_SITE_KEEP = 8

_lock = threading.Lock()
_HISTORY: Dict[str, "deque[dict]"] = {}
_REASONS: Dict[str, List[dict]] = {}
_REASONS_KEEP = 64


def record_miss(site: str, key) -> dict:
    """Record one compile-cache miss for ``key`` (a CacheKey) at
    ``site``; returns the structured reason::

        {"site": ..., "components": ["avals"], "first": False,
         "against": <index of the nearest prior sig>}

    ``components`` is ``["first"]`` for a site's first-ever compile
    (nothing to diff against) and ``["unknown"]`` when every tracked
    component matched the nearest prior signature (the identity
    differs only in untracked parts — still recorded, never silent).

    Never raises: the callers sit on compile paths, and diagnostics
    must not be able to break a build.
    """
    try:
        sig = key.component_digests()
    except Exception:  # noqa: BLE001 — a component repr may refuse to render
        sig = {"undigestable": "?"}
    with _lock:
        hist = _HISTORY.get(site)
        if hist is None:
            hist = _HISTORY[site] = deque(maxlen=_SITE_KEEP)
        nearest = None
        nearest_i = None
        best = -1
        for i, prev in enumerate(hist):
            overlap = sum(1 for name, dig in sig.items()
                          if prev.get(name) == dig)
            if overlap > best:
                best, nearest, nearest_i = overlap, prev, i
        if nearest is None:
            changed = ["first"]
        else:
            changed = sorted(
                name for name in set(sig) | set(nearest)
                if sig.get(name) != nearest.get(name)) or ["unknown"]
        hist.append(dict(sig))
        reason = {"site": site, "components": changed,
                  "first": nearest is None, "against": nearest_i}
        per = _REASONS.setdefault(site, [])
        per.append(reason)
        del per[:-_REASONS_KEEP]
    # telemetry + the mxprof stream OUTSIDE the provenance lock (the
    # instrument accessors and the recorder hold their own locks)
    for comp in changed:
        _ins.compile_reason_total(site, comp).inc()
    from .. import mxblackbox as _bb

    if _bb._ACTIVE:
        _bb.emit("compile", f"compile miss at '{site}'",
                 site=site, components=changed,
                 first=nearest is None)
    snk = _tracing._SINK
    if snk is not None:
        on_reason = getattr(snk, "on_compile_reason", None)
        if on_reason is not None:
            on_reason(site, changed)
    return reason


def history(site: Optional[str] = None):
    """Recorded miss reasons — for one site (list) or all sites
    (dict).  Bounded per site; newest last."""
    with _lock:
        if site is not None:
            return [dict(r) for r in _REASONS.get(site, ())]
        return {s: [dict(r) for r in rs] for s, rs in _REASONS.items()}


def clear() -> None:
    """Drop all provenance state (tests)."""
    with _lock:
        _HISTORY.clear()
        _REASONS.clear()
