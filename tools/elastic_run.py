#!/usr/bin/env python
"""Elastic job supervisor (ISSUE 15): launch N workers, detect a dead
or hung rank, recover the job.

Thin CLI over :class:`mxnet_tpu.resilience.elastic.Supervisor` — the
detection/coordination/commit-marker logic lives in the framework so
real launchers can embed it; this tool adds argv plumbing, a built-in
demo training worker (the chaos e2e fixture), and a JSON report.

    # supervise your own worker command (rank env contract exported):
    python tools/elastic_run.py --workers 4 --dir /ckpt/job1 \
        --mode shrink -- python train.py --my-args

    # the built-in demo worker (deterministic MLP, dist_sync kvstore,
    # per-rank AutoCheckpoint, heartbeats) with a chaos kill of rank 1
    # at its 4th step, recovered in replace mode:
    JAX_PLATFORMS=cpu python tools/elastic_run.py --workers 2 --demo \
        --cpu --steps 8 --chaos "elastic.worker@4:die:rank=1"

Each worker sees ``MXNET_ELASTIC=1``, ``MXNET_ELASTIC_DIR/RANK/WORLD``
plus the dmlc launcher contract (fresh coordinator port per
generation) and a collective watchdog (``MXNET_KVSTORE_TIMEOUT``).
Failure recovery: wind down survivors (SIGTERM -> preemption seam ->
sync checkpoint -> reserved rc), elect the job-level commit marker
(one step dir every restarted rank resumes from — steps can never mix
across ranks), restart in **replace** (same world) or **shrink**
(world minus the failed ranks) mode, bounded by the restart budget.
The report records per-epoch MTTR (detection -> first post-resume
step, watched via the heartbeat step stamps).

Exit: 0 when the job completed, 1 when it died (budget exhausted).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


# ---------------------------------------------------------------------------
# built-in demo worker: the smallest real multi-process training job
# with the full elastic contract (the chaos e2e + bench fixture)
# ---------------------------------------------------------------------------

def demo_worker(args) -> int:
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, nd, resilience
    from mxnet_tpu.gluon import Trainer, nn
    from mxnet_tpu.parallel import dist
    from mxnet_tpu.resilience import elastic

    dist.init()
    edir = elastic.shared_dir()
    rank, world = elastic.rank(), elastic.world()
    gb = args.global_batch

    # every rank must build the SAME model and data (the scaling_bench
    # parity lesson): seed the framework + numpy before init, generate
    # the GLOBAL batch everywhere, shard it disjointly by rank
    np.random.seed(args.seed)
    mx.random.seed(args.seed)
    rng = np.random.RandomState(args.seed)
    batches = [(rng.rand(gb, 16).astype("f4"),
                rng.rand(gb, 4).astype("f4"))
               for _ in range(args.steps)]
    net = nn.Dense(4, in_units=16, prefix="elastic_")
    net.initialize(ctx=mx.cpu())

    kv = "dist_sync" if world > 1 else "device"
    tr = Trainer(net.collect_params(), "sgd",
                 {"learning_rate": 0.05, "momentum": 0.9},
                 kvstore=kv, update_on_kvstore=False)
    pos = {"next_batch": 0}
    ck = resilience.AutoCheckpoint(
        os.path.join(edir, f"rank{rank}"), tr,
        every_n_steps=args.ckpt_every, async_save=False,
        state_provider=lambda: dict(pos))
    elastic.install_winddown()

    start = 0
    cpath = elastic.committed_resume_path(edir)
    if cpath is not None:
        # the commit marker carries the mxblackbox incident id of the
        # failure epoch this resume recovers from — it stamps the
        # goodput rank_failure_recovery window
        commit = elastic.read_commit(edir) or {}
        meta = ck.resume(path=cpath, incident=commit.get("incident"))
        # the demo maps one batch to one step, so the committed step
        # counter IS the resume index (the commit marker guarantees
        # every rank picked the same one)
        start = int(meta["step"])
    wc = elastic.WorkerContext()
    wc.heartbeat.beat(step=start)

    per = gb // world
    sl = slice(rank * per, (rank + 1) * per) if world > 1 \
        else slice(None)
    with elastic.guard(auto_ckpt=ck):
        for i in range(start, args.steps):
            xb, yb = batches[i]
            pos["next_batch"] = i + 1
            with autograd.record():
                loss = ((net(nd.array(xb[sl], ctx=mx.cpu()))
                         - nd.array(yb[sl], ctx=mx.cpu())) ** 2).sum()
            loss.backward()
            tr.step(gb)  # sum-loss backward + global bs = global mean
            wc.on_step(i + 1)
        # the reported loss is a POST-final-update forward pass on the
        # last batch — the one definition every path shares: a normal
        # run, a recovered run, and a resume that landed past the end
        # (commit step == steps) all report the same quantity, so the
        # bench's twin-parity comparison is apples to apples
        xb, yb = batches[-1]
        with autograd.pause():
            final = ((net(nd.array(xb[sl], ctx=mx.cpu()))
                      - nd.array(yb[sl], ctx=mx.cpu())) ** 2).sum()
        local = float(final.asnumpy().sum())
        gsum = float(dist.allgather_np(np.asarray(local)).sum())
        if rank == 0:
            result = {"loss": round(gsum / gb, 8), "world": world,
                      "steps": args.steps, "t_unix": time.time()}
            tmp = os.path.join(edir, ".tmp-result.json")
            with open(tmp, "w") as f:
                json.dump(result, f)
            os.replace(tmp, os.path.join(edir, "result.json"))
    return 0


# ---------------------------------------------------------------------------
# supervisor CLI
# ---------------------------------------------------------------------------

def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="supervise an N-rank training job with coordinated "
                    "rank-failure recovery (shrink/replace restarts)")
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--mode", choices=["replace", "shrink"],
                    default="replace")
    ap.add_argument("--dir", default=None,
                    help="shared elastic dir (default: a fresh tempdir)")
    ap.add_argument("--max-restarts", type=int, default=None)
    ap.add_argument("--hb-timeout", type=float, default=None,
                    help="heartbeat staleness -> hung (default: "
                         "MXNET_ELASTIC_HEARTBEAT_TIMEOUT_S)")
    ap.add_argument("--collective-timeout", type=float, default=None,
                    help="MXNET_KVSTORE_TIMEOUT exported to workers "
                         "(default: the heartbeat timeout)")
    ap.add_argument("--grace", type=float, default=None,
                    help="wind-down grace before SIGKILL")
    ap.add_argument("--startup-timeout", type=float, default=None,
                    help="a rank with NO heartbeat stamp past this "
                         "window is classified hung (default: "
                         "max(60, 4x hb timeout); 0 disables for "
                         "worker commands that never beat)")
    ap.add_argument("--poll", type=float, default=0.25)
    ap.add_argument("--chaos", default=None,
                    help="MXNET_CHAOS_SPEC exported to GENERATION 0 "
                         "only (e.g. 'elastic.worker@4:die:rank=1')")
    ap.add_argument("--cpu", action="store_true",
                    help="pin workers to the single-device CPU+gloo "
                         "backend (dev box / CI)")
    ap.add_argument("--demo", action="store_true",
                    help="supervise the built-in demo training worker")
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--ckpt-every", type=int, default=2)
    ap.add_argument("--global-batch", type=int, default=8)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out", default=None,
                    help="also write the report JSON here")
    ap.add_argument("--_demo-worker", action="store_true",
                    help=argparse.SUPPRESS)
    ap.add_argument("worker_cmd", nargs="*",
                    help="worker command (after --); omit with --demo")
    args = ap.parse_args(argv)

    if args._demo_worker:
        return demo_worker(args)

    from mxnet_tpu.resilience.elastic import Supervisor

    if args.demo:
        cmd = [sys.executable, os.path.abspath(__file__),
               "--_demo-worker", "--steps", str(args.steps),
               "--ckpt-every", str(args.ckpt_every),
               "--global-batch", str(args.global_batch),
               "--seed", str(args.seed)]
    elif args.worker_cmd:
        cmd = args.worker_cmd
    else:
        print("error: give a worker command or --demo", file=sys.stderr)
        return 2

    directory = args.dir or tempfile.mkdtemp(prefix="mx-elastic-")
    base_env = dict(os.environ)
    if args.cpu:
        base_env["PALLAS_AXON_POOL_IPS"] = ""
        base_env["JAX_PLATFORMS"] = "cpu"
        base_env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    if args.chaos:
        base_env["MXNET_CHAOS"] = "1"
        base_env["MXNET_CHAOS_SPEC"] = args.chaos

    # convert an outer SIGTERM (a CI timeout terminating this
    # supervisor) into SystemExit so Supervisor.run's teardown kills
    # the live worker generation instead of orphaning it
    import signal as _signal

    _signal.signal(_signal.SIGTERM, lambda s, f: sys.exit(143))

    sup = Supervisor(cmd, world=args.workers, directory=directory,
                     mode=args.mode, max_restarts=args.max_restarts,
                     hb_timeout_s=args.hb_timeout,
                     grace_s=args.grace,
                     collective_timeout_s=args.collective_timeout,
                     poll_s=args.poll,
                     startup_timeout_s=args.startup_timeout,
                     base_env=base_env)
    t0 = time.time()
    report = sup.run()
    report["duration_s"] = round(time.time() - t0, 3)
    report["dir"] = directory
    try:
        with open(os.path.join(directory, "result.json")) as f:
            report["result"] = json.load(f)
    except (OSError, ValueError):
        pass
    line = json.dumps(report)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    return 0 if report.get("ok") else 1


if __name__ == "__main__":
    sys.exit(main())
