"""All five BASELINE.md benchmark configs, measured on one chip.

bench.py stays the driver's official single-metric artifact (ResNet-50);
this harness measures the full config table — MNIST MLP, ResNet-50,
BERT-base pretrain, SSD-300-ResNet50, Transformer NMT — each as ONE
jitted train step (forward+backward+update) via parallel.SPMDTrainer,
plus the two head-to-head variants VERDICT round 3 asked for:
ResNet-50 fused-conv-BN (MXNET_FUSED_CONVBN=1) and BERT with the Pallas
attention kernel disabled (MXNET_USE_PALLAS=0).

Each measurement runs in its own bounded child process (same
hung-tunnel discipline as bench.py: the parent never imports jax), with
env-var variants isolated per process.  Output: one JSON line per
measurement on stdout and the collected table in BENCH_ALL.json.

Usage:
    python bench_all.py                  # TPU, all configs
    python bench_all.py --config bert_base --variant no_pallas
    python bench_all.py --cpu-smoke      # tiny shapes, CPU, CI self-test
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.abspath(__file__))


# ---------------------------------------------------------------------------
# measurement children (run in their own process; may import jax)
# ---------------------------------------------------------------------------

def _measure_loop(step_fn, unit_count, steps, warmup):
    """Time `steps` calls of step_fn after warmup; step_fn returns the
    loss NDArray whose .asnumpy() is the only sync point."""
    import numpy as np

    # at least one unmeasured call: compilation must stay out of the
    # timed window (and `loss` must be bound even for --warmup 0)
    for _ in range(max(warmup, 1)):
        loss = step_fn()
    loss.asnumpy()
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = step_fn()
    lval = float(loss.asnumpy())
    dt = time.perf_counter() - t0
    assert np.isfinite(lval), f"non-finite loss {lval}"
    return unit_count * steps / dt, lval


class _Identity:
    def __call__(self, out, *labels):
        return out


def _spmd_trainer(net, optimizer, opt_params):
    from mxnet_tpu import parallel

    mesh = parallel.make_mesh(dp=1)
    mesh.__enter__()
    return parallel.SPMDTrainer(net, _Identity(), optimizer, opt_params,
                                n_labels=0)


def bench_mnist_mlp(args):
    """BASELINE config 1 — examples/gluon/mnist.py MLP, synthetic data."""
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.gluon.block import HybridBlock

    bs = 64 if args.cpu_smoke else 512

    class Step(HybridBlock):
        def __init__(self):
            super().__init__()
            with self.name_scope():
                self.net = nn.HybridSequential(prefix="")
                self.net.add(nn.Dense(128, activation="relu"))
                self.net.add(nn.Dense(64, activation="relu"))
                self.net.add(nn.Dense(10))

        def hybrid_forward(self, F, x, y):
            import jax
            import jax.numpy as jnp

            logits = self.net(x)
            lsm = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
            nll = -jnp.take_along_axis(
                lsm, y[:, None].astype(jnp.int32), -1)[:, 0]
            return nll.mean()

    step_blk = Step()
    step_blk.initialize(mx.initializer.Xavier(), ctx=mx.cpu())
    rng = np.random.RandomState(0)
    x = rng.rand(bs, 784).astype(np.float32)
    y = rng.randint(0, 10, (bs,)).astype(np.int32)
    # deferred shapes resolve through the inner net: the Step wrapper's
    # jnp loss math is traced-only
    with mx.autograd.pause():
        step_blk.net(mx.nd.array(x))
    trainer = _spmd_trainer(step_blk, "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9})
    xd, yd = trainer._place(x, None), trainer._place(y, None)
    tp, lval = _measure_loop(lambda: trainer.step(xd, yd), bs,
                             args.steps, args.warmup)
    return {"metric": "mnist_mlp_train_throughput", "value": round(tp, 1),
            "unit": "samples/s", "loss": round(lval, 4)}


def bench_resnet50(args):
    """BASELINE config 2 — delegated to bench.py's exact measurement
    (variant `fused` = MXNET_FUSED_CONVBN=1, set by the parent)."""
    import bench as bench_mod

    class A:
        cpu_smoke = args.cpu_smoke
        batch_size, image_size = 256, 224
        steps, warmup = args.steps, args.warmup
        dtype, layout = "bfloat16", "NHWC"
        no_fused = True  # 'default' means the op-granular baseline; the
        #                  fused_convbn variant is its own child run

    return bench_mod.run_benchmark(A())


def bench_bert_base(args):
    """BASELINE config 3 — MLM+NSP pretrain step, seq 128 (GluonNLP
    run_pretraining.py counterpart; variant `no_pallas` = XLA attention)."""
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.block import HybridBlock
    from mxnet_tpu.gluon.model_zoo.bert import get_bert_model

    if args.cpu_smoke:
        bs, seq, vocab = 2, 32, 1000
        kw = dict(num_layers=2, units=64, hidden_size=128, num_heads=4,
                  max_length=seq)
    else:
        bs, seq, vocab = 32, 128, 30522
        kw = dict(max_length=512)

    class Step(HybridBlock):
        def __init__(self):
            super().__init__()
            with self.name_scope():
                self.bert = get_bert_model("bert_12_768_12",
                                           vocab_size=vocab, **kw)

        def hybrid_forward(self, F, tokens, segments, vlen, mlm_labels,
                           mlm_weight, nsp_labels):
            import jax
            import jax.numpy as jnp

            seq_out, pooled = self.bert(tokens, segments, vlen)
            mlm_scores = self.bert.decode_mlm(seq_out)
            nsp_scores = self.bert.classify_nsp(pooled)
            lsm = jax.nn.log_softmax(mlm_scores.astype(jnp.float32), -1)
            nll = -jnp.take_along_axis(
                lsm, mlm_labels[..., None].astype(jnp.int32), -1)[..., 0]
            mlm_l = ((nll * mlm_weight).sum()
                     / jnp.maximum(mlm_weight.sum(), 1.0))
            nsp_lsm = jax.nn.log_softmax(nsp_scores.astype(jnp.float32), -1)
            nsp_l = -jnp.take_along_axis(
                nsp_lsm, nsp_labels[:, None].astype(jnp.int32), -1)[:, 0]
            return mlm_l + nsp_l.mean()

    step_blk = Step()
    step_blk.initialize(mx.initializer.Normal(0.02), ctx=mx.cpu())
    rng = np.random.RandomState(0)
    tokens = rng.randint(5, vocab, (bs, seq)).astype(np.int32)
    segments = np.zeros((bs, seq), np.int32)
    vlen = np.full((bs,), seq, np.float32)
    mlm_labels = rng.randint(5, vocab, (bs, seq)).astype(np.int32)
    mlm_weight = (rng.rand(bs, seq) < 0.15).astype(np.float32)
    nsp_labels = rng.randint(0, 2, (bs,)).astype(np.int32)
    with mx.autograd.pause():
        # warm inputs pinned to the init ctx: on a TPU host the default
        # context is tpu(0), and cpu-initialized params must not meet
        # tpu-resident inputs in the eager warm pass
        seq_out, pooled = step_blk.bert(
            mx.nd.array(tokens, ctx=mx.cpu()),
            mx.nd.array(segments, ctx=mx.cpu()),
            mx.nd.array(vlen, ctx=mx.cpu()))
        step_blk.bert.decode_mlm(seq_out)
        step_blk.bert.classify_nsp(pooled)
    if not args.cpu_smoke:
        step_blk.cast("bfloat16")
    trainer = _spmd_trainer(step_blk, "adam", {"learning_rate": 1e-4})
    placed = [trainer._place(a, None) for a in
              (tokens, segments, vlen, mlm_labels, mlm_weight, nsp_labels)]
    tp, lval = _measure_loop(lambda: trainer.step(*placed), bs,
                             args.steps, args.warmup)
    return {"metric": "bert_base_pretrain_throughput",
            "value": round(tp, 1), "unit": "samples/s",
            "seq_len": seq, "loss": round(lval, 4)}


def bench_ssd_resnet50(args):
    """BASELINE config 4 — SSD-300-ResNet50 train step with the GluonCV
    SSDMultiBoxLoss (targets precomputed host-side, as GluonCV's default
    training loop does with its label batchify)."""
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.block import HybridBlock
    from mxnet_tpu.gluon.model_zoo.detection import (SSDMultiBoxLoss,
                                                     ssd_300_resnet50_v1)

    bs = 1 if args.cpu_smoke else 32
    size = 300  # the anchor spec is keyed to the 300x300 input

    class Step(HybridBlock):
        def __init__(self):
            super().__init__()
            with self.name_scope():
                self.ssd = ssd_300_resnet50_v1(classes=20)
                self.loss = SSDMultiBoxLoss()

        def hybrid_forward(self, F, x, cls_t, box_t):
            cls_p, box_p, _anchors = self.ssd(x)
            return self.loss(cls_p, box_p, cls_t, box_t)

    step_blk = Step()
    step_blk.initialize(mx.initializer.Xavier(), ctx=mx.cpu())
    rng = np.random.RandomState(0)
    x = rng.rand(bs, 3, size, size).astype(np.float32)
    with mx.autograd.pause():
        n_anchors = int(step_blk.ssd(
            mx.nd.array(x[:1], ctx=mx.cpu()))[0].shape[1])
    cls_t = rng.randint(-1, 21, (bs, n_anchors)).astype(np.float32)
    box_t = (rng.randn(bs, n_anchors, 4) * 0.1).astype(np.float32)
    if not args.cpu_smoke:
        step_blk.cast("bfloat16")
    trainer = _spmd_trainer(step_blk, "sgd",
                            {"learning_rate": 0.01, "momentum": 0.9,
                             "wd": 5e-4})
    placed = [trainer._place(a, None) for a in (x, cls_t, box_t)]
    tp, lval = _measure_loop(lambda: trainer.step(*placed), bs,
                             args.steps, args.warmup)
    return {"metric": "ssd300_resnet50_train_throughput",
            "value": round(tp, 1), "unit": "img/s",
            "anchors": n_anchors, "loss": round(lval, 4)}


def bench_transformer_nmt(args):
    """BASELINE config 5 — transformer-base en-de train step (Sockeye /
    GluonNLP counterpart), label-smoothed CE, one (64,64) bucket."""
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.block import HybridBlock
    from mxnet_tpu.gluon.model_zoo.transformer import get_transformer_model

    if args.cpu_smoke:
        bs, slen, vocab = 2, 16, 1000
        kw = dict(num_layers=2, units=64, hidden_size=128, num_heads=4)
    else:
        bs, slen, vocab = 64, 64, 32000
        kw = {}

    class Step(HybridBlock):
        def __init__(self):
            super().__init__()
            with self.name_scope():
                self.net = get_transformer_model(
                    "transformer_base", src_vocab_size=vocab,
                    tgt_vocab_size=vocab, **kw)

        def hybrid_forward(self, F, src, tgt_in, src_valid, tgt_valid,
                           tgt_out):
            import jax
            import jax.numpy as jnp

            logits = self.net(src, tgt_in, src_valid, tgt_valid)
            lsm = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
            eps = 0.1
            nll = -jnp.take_along_axis(
                lsm, tgt_out[..., None].astype(jnp.int32), -1)[..., 0]
            smooth = -lsm.mean(-1)
            steps_ = jax.lax.broadcasted_iota(
                jnp.int32, nll.shape, 1).astype(jnp.float32)
            mask = (steps_ < tgt_valid[:, None].astype(jnp.float32))
            per_tok = ((1 - eps) * nll + eps * smooth) * mask
            return per_tok.sum() / jnp.maximum(mask.sum(), 1.0)

    step_blk = Step()
    step_blk.initialize(mx.initializer.Xavier(), ctx=mx.cpu())
    rng = np.random.RandomState(0)
    src = rng.randint(4, vocab, (bs, slen)).astype(np.int32)
    tgt_in = rng.randint(4, vocab, (bs, slen)).astype(np.int32)
    tgt_out = rng.randint(4, vocab, (bs, slen)).astype(np.int32)
    sv = np.full((bs,), slen, np.float32)
    tv = np.full((bs,), slen, np.float32)
    with mx.autograd.pause():
        step_blk.net(mx.nd.array(src, ctx=mx.cpu()),
                     mx.nd.array(tgt_in, ctx=mx.cpu()),
                     mx.nd.array(sv, ctx=mx.cpu()),
                     mx.nd.array(tv, ctx=mx.cpu()))
    if not args.cpu_smoke:
        step_blk.cast("bfloat16")
    trainer = _spmd_trainer(step_blk, "adam", {"learning_rate": 3e-4})
    placed = [trainer._place(a, None) for a in (src, tgt_in, sv, tv,
                                                tgt_out)]
    tp, lval = _measure_loop(lambda: trainer.step(*placed), bs * slen,
                             args.steps, args.warmup)
    return {"metric": "transformer_nmt_train_throughput",
            "value": round(tp, 1), "unit": "tokens/s",
            "bucket": [slen, slen], "loss": round(lval, 4)}


CONFIGS = {
    "mnist_mlp": bench_mnist_mlp,
    "resnet50": bench_resnet50,
    "bert_base": bench_bert_base,
    "ssd_resnet50": bench_ssd_resnet50,
    "transformer_nmt": bench_transformer_nmt,
}

# (config, variant-name, extra env) — variants isolate env flags per child
RUNS = [
    ("mnist_mlp", "default", {}),
    ("resnet50", "default", {}),
    ("resnet50", "fused_convbn", {"MXNET_FUSED_CONVBN": "1",
                                  # ~20 fused-unit configs probe-compile
                                  # at 3-17s each; the 300s default
                                  # would silently mix fallback layers
                                  "MXNET_PALLAS_PROBE_BUDGET": "900"}),
    ("bert_base", "default", {}),
    ("bert_base", "no_pallas", {"MXNET_USE_PALLAS": "0"}),
    ("ssd_resnet50", "default", {}),
    ("transformer_nmt", "default", {}),
]


def _probe_backend(timeout_s):
    import bench as bench_mod

    return bench_mod._probe_backend(timeout_s)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", choices=sorted(CONFIGS), default=None)
    ap.add_argument("--variant", default="default")
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--warmup", type=int, default=3)
    ap.add_argument("--cpu-smoke", action="store_true")
    ap.add_argument("--init-timeout", type=float, default=240.0)
    ap.add_argument("--run-timeout", type=float, default=1500.0)
    ap.add_argument("--out", default=os.path.join(_REPO, "BENCH_ALL.json"))
    ap.add_argument("--_child", action="store_true", help=argparse.SUPPRESS)
    args = ap.parse_args()

    if args.cpu_smoke:
        import jax

        jax.config.update("jax_platforms", "cpu")
        args.steps, args.warmup = 3, 1

    if args._child or (args.cpu_smoke and args.config):
        res = CONFIGS[args.config](args)
        res["variant"] = args.variant
        print(json.dumps(res))
        return 0

    if args.cpu_smoke:
        for name in sorted(CONFIGS):
            args.config = name
            res = CONFIGS[name](args)
            res["variant"] = "cpu_smoke"
            print(json.dumps(res))
        return 0

    # ---- parent: bounded children, one per (config, variant) ----
    if args.variant != "default" and args.config is None:
        ap.error("--variant requires --config")
    runs = [r for r in RUNS if args.config in (None, r[0])
            and (args.config is None or args.variant in ("default", r[1]))]
    ok, diag = _probe_backend(args.init_timeout)
    results = []
    if not ok:
        results.append({"error": f"infra-down: {diag}"})
    else:
        for name, variant, env in runs:
            cmd = [sys.executable, os.path.abspath(__file__), "--_child",
                   "--config", name, "--variant", variant,
                   "--steps", str(args.steps), "--warmup", str(args.warmup)]
            # a raised probe budget must come with a raised child bound,
            # or worst-case probing converts "some fallback layers" into
            # "no fused number at all"
            extra = float(env.get("MXNET_PALLAS_PROBE_BUDGET", 0))
            try:
                p = subprocess.run(cmd, capture_output=True, text=True,
                                   timeout=args.run_timeout + extra,
                                   env={**os.environ, **env})
            except subprocess.TimeoutExpired:
                results.append({"metric": name, "variant": variant,
                                "error": "timeout"})
                continue
            line = next((ln for ln in reversed(p.stdout.splitlines())
                         if ln.startswith("{")), None)
            if p.returncode == 0 and line:
                results.append(json.loads(line))
                print(line)
            else:
                tail = (p.stderr.strip().splitlines() or ["?"])[-1][:300]
                results.append({"metric": name, "variant": variant,
                                "error": tail})
                print(json.dumps(results[-1]))

    with open(args.out, "w") as f:
        json.dump({"when": time.strftime("%Y-%m-%d %H:%M:%S"),
                   "results": results}, f, indent=1)
    return 0


if __name__ == "__main__":
    sys.exit(main())
