"""Pipeline parallelism over the 'pp' mesh axis.

Beyond-reference capability (SURVEY.md §2d: the reference's only model
parallelism is manual `group2ctx` placement).  Here a stack of identical
stages (e.g. transformer blocks) has its stacked parameters sharded over
'pp' — device i holds stage i — and microbatches stream through the ring:
each tick every device runs its stage on its current activation, then the
activations `ppermute` one hop forward.  After n_micro + n_stages - 1
ticks all microbatches have exited the last stage (GPipe schedule; bubble
= (S-1)/(M+S-1)).

The formulation is pure SPMD (shard_map + ppermute over ICI neighbours),
so XLA overlaps the activation transfer with the next tick's compute.
"""
from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..base import MXNetError
from ._compat import shard_map_unchecked
from .mesh import DeviceMesh, current_mesh

__all__ = ["pipeline_apply", "stack_stage_params"]


def stack_stage_params(params_list):
    """[{name: arr}, ...] per stage -> {name: arr[S, ...]} stacked pytree
    (the layout whose leading dim shards over 'pp')."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *params_list)


def _pipeline_local(stage_params, x_micro, stage_fn, axis_name):
    """Body inside shard_map.

    stage_params: pytree with leading stage dim of size 1 (this device's
        stage), i.e. {name: [1, ...]}.
    x_micro: [M_local?…] — microbatches replicated along pp: [M, B, ...].
    """
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    sparams = jax.tree_util.tree_map(lambda a: a[0], stage_params)
    m = x_micro.shape[0]
    ticks = m + n - 1
    perm = [(i, (i + 1) % n) for i in range(n)]

    state = jnp.zeros_like(x_micro[0])               # current activation
    outs = jnp.zeros_like(x_micro)                   # collected on last stage

    def body(t, carry):
        state, outs = carry
        # stage 0 ingests microbatch t (if any) instead of the ring input
        feed = x_micro[jnp.minimum(t, m - 1)]
        x = jnp.where(idx == 0, jnp.where(t < m, feed, state), state)
        y = stage_fn(sparams, x)
        # last stage emits microbatch t - (n - 1)
        out_i = t - (n - 1)
        outs = jnp.where(
            (idx == n - 1) & (out_i >= 0),
            outs.at[jnp.maximum(out_i, 0)].set(y), outs)
        state = lax.ppermute(y, axis_name, perm)
        return state, outs

    _, outs = lax.fori_loop(0, ticks, body, (state, outs))
    # only the last stage's copy is meaningful — broadcast along pp via a
    # masked psum so the result is replicated on every stage
    outs = lax.psum(jnp.where(idx == n - 1, outs, jnp.zeros_like(outs)),
                    axis_name)
    return outs


def pipeline_apply(stage_fn: Callable, stacked_params, x,
                   n_microbatch: int, *, mesh: Optional[DeviceMesh] = None,
                   axis_name: str = "pp", batch_axes=("dp", "fsdp")):
    """Run `x` [B, ...] through S pipelined stages.

    stage_fn(params_i, x) -> y with y.shape == x.shape (homogeneous
    stages — the transformer-block case).
    stacked_params: pytree with leading dim S == mesh.size('pp').
    """
    mesh = mesh or current_mesh()
    if mesh is None:
        raise MXNetError("pipeline_apply requires an active mesh")
    n = mesh.size(axis_name)
    first = jax.tree_util.tree_leaves(stacked_params)[0]
    if first.shape[0] != n:
        raise MXNetError(
            f"stacked stage dim {first.shape[0]} != mesh '{axis_name}' size {n}")
    if x.shape[0] % n_microbatch:
        raise MXNetError(
            f"batch {x.shape[0]} not divisible by n_microbatch {n_microbatch}")
    if n == 1:
        sparams = jax.tree_util.tree_map(lambda a: a[0], stacked_params)
        return stage_fn(sparams, x)

    mb = x.reshape((n_microbatch, x.shape[0] // n_microbatch) + x.shape[1:])
    batch = tuple(a for a in batch_axes if a in mesh) or None
    x_spec = P(None, batch, *([None] * (x.ndim - 1)))
    p_spec = jax.tree_util.tree_map(
        lambda a: P(axis_name, *([None] * (a.ndim - 1))), stacked_params)
    fn = shard_map_unchecked(
        functools.partial(_pipeline_local, stage_fn=stage_fn,
                          axis_name=axis_name),
        mesh=mesh.mesh, in_specs=(p_spec, x_spec), out_specs=x_spec)
    out = fn(stacked_params, mb)
    return out.reshape(x.shape)
