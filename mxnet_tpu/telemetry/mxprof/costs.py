"""Executable cost accounting: FLOPs/bytes per compiled program.

``compiled.cost_analysis()`` is XLA's own static cost model for a
compiled executable — FLOPs and bytes accessed.  It is captured ONCE
per executable at the compile-cache sites (fused step, SPMD step, the
gspmd whole-step trainer, serving buckets) and stored next to the
cached executable, so a program that came back from the persistent
compile cache keeps its cost metadata the same as a fresh build: the
analysis runs on the loaded executable object, not on the build.

Combined with step wall time (the flight recorder) this yields
``mx_step_mfu`` and the per-step roofline verdict.  The MFU
denominator is the per-device peak FLOP/s: ``MXNET_PEAK_FLOPS``
overrides; otherwise the device-kind table below answers for known
TPU generations, and an unknown device reports MFU as None — a
made-up utilization is worse than none.
"""
from __future__ import annotations

import threading
from typing import Dict, NamedTuple, Optional, Tuple

from ...util import env as _env

__all__ = ["Cost", "executable_cost", "peak_flops",
           "backend_initialized", "note", "notes", "hlo_fingerprint"]


class Cost(NamedTuple):
    flops: float
    bytes_accessed: float


def executable_cost(compiled) -> Optional[Cost]:
    """Cost of one compiled executable, or None when the backend (or a
    deserialized payload) does not support cost analysis.  Never
    raises — attribution must not break a compile."""
    try:
        ca = compiled.cost_analysis()
    except Exception:  # noqa: BLE001 — backend/payload may not support it
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    if not isinstance(ca, dict):
        return None
    try:
        flops = float(ca.get("flops", 0.0) or 0.0)
        nbytes = float(ca.get("bytes accessed",
                              ca.get("bytes_accessed", 0.0)) or 0.0)
    except (TypeError, ValueError):
        return None
    if flops <= 0.0 and nbytes <= 0.0:
        return None
    return Cost(flops, nbytes)


# peak dense FLOP/s per chip by device-kind substring (bf16 MXU peak,
# public TPU specs); matched case-insensitively, first hit wins.  CPU
# and unknown accelerators resolve to None.
_PEAK_BY_KIND: Tuple[Tuple[str, float], ...] = (
    ("v5p", 459e12),
    ("v5e", 197e12),
    ("v5litepod", 197e12),
    ("v6e", 918e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)


def backend_initialized() -> bool:
    """Whether a jax backend is up — an 'unknown' peak answered while
    the backend is still down is provisional (the device kind could
    not be read yet), not final."""
    try:
        import jax

        return bool(getattr(jax._src.xla_bridge, "_backends", None))
    except Exception:  # noqa: BLE001
        return False


def peak_flops(device_kind: Optional[str] = None
               ) -> Tuple[Optional[float], str]:
    """(per-device peak FLOP/s, source) — source is ``env`` / ``table``
    / ``unknown``.  ``device_kind`` defaults to the first visible
    device's kind (resolved lazily; never initializes a backend that
    is not already up)."""
    v = _env.get_float("MXNET_PEAK_FLOPS")
    if v:
        return float(v), "env"
    if device_kind is None:
        try:
            import jax

            if not getattr(jax._src.xla_bridge, "_backends", None):
                return None, "unknown"
            device_kind = jax.devices()[0].device_kind
        except Exception:  # noqa: BLE001
            return None, "unknown"
    kind = (device_kind or "").lower()
    for sub, peak in _PEAK_BY_KIND:
        if sub in kind:
            return peak, "table"
    return None, "unknown"


# ---- per-site cost notes (what dump() reports) ------------------------

_NOTES_MAX = 256
_notes_lock = threading.Lock()
_notes: Dict[str, Dict[str, dict]] = {}


def note(site: str, key: str, cost: Optional[Cost],
         fingerprint: Optional[str] = None) -> None:
    """Remember one executable's cost (and, when known, its HLO-module
    fingerprint) under (site, key) for dumps — bounded per site so
    long-lived processes stay flat.  The fingerprint rides beside the
    cost so perf attribution can say "the compiled program did (not)
    change" across runs."""
    if cost is None and fingerprint is None:
        return
    with _notes_lock:
        per = _notes.setdefault(site, {})
        if key not in per and len(per) >= _NOTES_MAX:
            per.pop(next(iter(per)))
        row = {}
        if cost is not None:
            row = {"flops": cost.flops,
                   "bytes_accessed": cost.bytes_accessed}
        if fingerprint is not None:
            row["hlo_fingerprint"] = fingerprint
        per[key] = row


def hlo_fingerprint(compiled, program_text: Optional[str] = None
                    ) -> Optional[str]:
    """sha256 identity of one executable's HLO module: the lowered
    program text when the caller has it (free — it was rendered for
    the cache key), else the compiled module's own text, else None
    (deserialized payloads may not render)."""
    import hashlib

    text = program_text
    if text is None:
        try:
            text = compiled.as_text()
        except Exception:  # noqa: BLE001 — best effort on loaded payloads
            return None
    if not text:
        return None
    return hashlib.sha256(text.encode()).hexdigest()


def notes() -> Dict[str, Dict[str, dict]]:
    with _notes_lock:
        return {s: dict(d) for s, d in _notes.items()}
