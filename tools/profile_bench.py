"""Capture + analyze an xplane trace of the ResNet-50 bench train step.

Writes a per-op-category device-time breakdown (the MFU analysis VERDICT
round 2 asked for).  Usage:
    python tools/profile_bench.py [--batch-size 256] [--steps 5] [--out DIR]
The fused paths profile through the same command via their env knobs:
    MXNET_FUSED_CONVBN=1 [MXNET_FUSED_CONVBN_BWD=1] python tools/profile_bench.py
Parses the xplane.pb with tensorflow's proto (no tensorboard needed).

The capture window runs through ``telemetry.mxtriage`` (the one
deep-capture path every surface shares), so the run is admission-gated,
indexed, and leaves an ``mxprof.json`` aggregate + ``meta.json``
beside the xplane files.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
import time
from collections import defaultdict


def capture(args) -> str:
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import parallel
    from mxnet_tpu.gluon import loss as gloss
    from mxnet_tpu.gluon.model_zoo import vision
    from mxnet_tpu.telemetry import mxtriage

    net = vision.resnet50_v1(classes=1000, layout=args.layout)
    net.initialize(mx.initializer.Xavier(magnitude=2.0), ctx=mx.cpu())
    with mx.autograd.pause():
        shape = ((1, 3, 32, 32) if args.layout == "NCHW" else (1, 32, 32, 3))
        net(mx.nd.zeros(shape, ctx=mx.cpu()))
    if args.dtype != "float32":
        net.cast(args.dtype)

    rng = np.random.RandomState(0)
    ishape = ((args.batch_size, 3, args.image_size, args.image_size)
              if args.layout == "NCHW"
              else (args.batch_size, args.image_size, args.image_size, 3))
    images = rng.rand(*ishape).astype(args.dtype)
    labels = rng.randint(0, 1000, size=(args.batch_size,)).astype(np.int32)

    mesh = parallel.make_mesh(dp=1)
    with mesh:
        trainer = parallel.SPMDTrainer(
            net, gloss.SoftmaxCrossEntropyLoss(), "sgd",
            {"learning_rate": 0.1, "momentum": 0.9, "wd": 1e-4})
        images = trainer._place(images, None)
        labels = trainer._place(labels, None)
        for _ in range(3):
            loss = trainer.step(images, labels)
        loss.asnumpy()

        t0 = time.perf_counter()
        for _ in range(args.steps):
            loss = trainer.step(images, labels)
        loss.asnumpy()
        dt = time.perf_counter() - t0
        print(f"throughput: {args.batch_size*args.steps/dt:.1f} img/s "
              f"({dt/args.steps*1e3:.1f} ms/step)")

        os.makedirs(args.out, exist_ok=True)
        # the one deep-capture path (admission-gated + indexed):
        # manual bracket around exactly the measured steps
        mxtriage.start_manual(args.out)
        try:
            for _ in range(args.steps):
                loss = trainer.step(images, labels)
            loss.asnumpy()
        finally:
            mxtriage.stop_manual()
    return args.out


# categorize by the op's own label (lhs of " = "), NOT by substring over
# the full event name — operand text would misattribute (e.g. "convert"
# matching "conv", fusions quoting %copy-done operands)
LABEL_CATEGORIES = [
    ("conv+fusion (convs, BN-bwd dx)", re.compile(r"^fusion$")),
    ("wgrad+update (add_convert)", re.compile(r"^add_convert_fusion$")),
    ("BN stat reduces (convert_reduce)", re.compile(r"^convert_reduce_fusion$")),
    ("relu/residual (maximum_add)", re.compile(r"^maximum_add_fusion$")),
    ("pool", re.compile(r"^(select_and_scatter|reduce-window)")),
    ("copies/slices", re.compile(r"^(copy|slice|bitcast)")),
    ("other fusions", re.compile(r"fusion$")),
]


def _label(name: str) -> str:
    lhs = name.split(" = ")[0].lstrip("%")
    return re.sub(r"[.\d]+$", "", lhs)


def analyze(logdir: str, steps: int):
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    pbs = glob.glob(os.path.join(logdir, "**", "*.xplane.pb"), recursive=True)
    if not pbs:
        print("no xplane.pb found under", logdir)
        return
    pb = max(pbs, key=os.path.getmtime)
    xs = xplane_pb2.XSpace()
    with open(pb, "rb") as f:
        xs.ParseFromString(f.read())

    for plane in xs.planes:
        if "TPU" not in plane.name:
            continue
        ev_meta = plane.event_metadata
        for line in plane.lines:
            if line.name != "XLA Ops":  # the non-overlapped device timeline
                continue
            op_time = defaultdict(int)
            total = 0
            for ev in line.events:
                lab = _label(ev_meta[ev.metadata_id].name)
                op_time[lab] += ev.duration_ps
                total += ev.duration_ps
            print(f"\n=== {plane.name} 'XLA Ops': "
                  f"{total/1e12*1e3/steps:.1f} ms/step ===")
            cat_time = defaultdict(int)
            for lab, t in op_time.items():
                for cat, pat in LABEL_CATEGORIES:
                    if pat.search(lab):
                        cat_time[cat] += t
                        break
                else:
                    cat_time["other"] += t
            for cat, t in sorted(cat_time.items(), key=lambda kv: -kv[1]):
                print(f"  {cat:36s} {t/1e12*1e3/steps:8.2f} ms/step  "
                      f"{100*t/total:5.1f}%")
            print("  top 15 op labels:")
            for lab, t in sorted(op_time.items(), key=lambda kv: -kv[1])[:15]:
                print(f"    {t/1e12*1e3/steps:8.3f} ms/step  {lab}")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=256)
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--layout", default="NHWC")
    ap.add_argument("--out", default="/tmp/xprof_bench")
    ap.add_argument("--analyze-only", action="store_true")
    args = ap.parse_args()
    if not args.analyze_only:
        capture(args)
    analyze(args.out, args.steps)


if __name__ == "__main__":
    sys.exit(main())
