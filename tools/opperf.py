"""Per-op micro-benchmark harness (counterpart of the reference's
benchmark/opperf/ — per-operator forward/backward latency so op-level
perf regressions show up in artifact diffs, SURVEY.md §6).

For each covered op, three timings (median-of-runs, µs/call):
  * eager   — the imperative NDArray path (CS1: python dispatch +
              registry invoke + async jax dispatch), fwd only
  * jit_fwd — the op compiled alone via jax.jit (what a traced program
              pays, minus fusion with neighbors)
  * jit_bwd — compiled VJP application (fwd+bwd program)

Run on CPU (pinned, for regression diffs) or TPU (the real numbers):
    python tools/opperf.py --out OPPERF.json          # current backend
    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python tools/opperf.py
    python tools/opperf.py --ops Convolution,dot      # subset

The committed OPPERF.json is the baseline; CI-style usage is to re-run
and diff `value` columns (>2x swings on the same backend are real).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def _specs(np, large):
    """op name -> (args, attrs). Shapes: `large` on accelerators
    (bandwidth-visible), small on CPU (keeps the sweep under a minute).
    Covers the hot families: MXU ops, normalization, elementwise,
    reductions, indexing, optimizer updates, vision/detection."""
    r = np.random.RandomState(0)

    def f(*shape):
        return r.rand(*shape).astype(np.float32)

    B, C, H = (64, 128, 56) if large else (8, 32, 14)
    S, U = (128, 768) if large else (16, 64)
    N = (1024, 4096) if large else (128, 256)
    sp = {
        # MXU
        "FullyConnected": ((f(B, N[0]), f(N[1], N[0]), f(N[1])),
                           {"num_hidden": N[1]}),
        "dot": ((f(N[0], N[0]), f(N[0], N[0])), {}),
        "batch_dot": ((f(16, S, 64), f(16, 64, S)), {}),
        "Convolution": ((f(B, C, H, H), f(C, C, 3, 3)),
                        {"kernel": (3, 3), "pad": (1, 1), "num_filter": C,
                         "no_bias": True}),
        "Deconvolution": ((f(B, C, H // 2, H // 2), f(C, C, 2, 2)),
                          {"kernel": (2, 2), "stride": (2, 2),
                           "num_filter": C, "no_bias": True}),
        # normalization / activation
        "BatchNorm": ((f(B, C, H, H), f(C), f(C), f(C), f(C) + 1.0),
                      {"_train": True}),
        "LayerNorm": ((f(B, S, U), f(U), f(U)), {"axis": -1}),
        "softmax": ((f(B, S, S),), {"axis": -1}),
        "log_softmax": ((f(B, N[1]),), {"axis": -1}),
        "Activation": ((f(B, C, H, H),), {"act_type": "relu"}),
        "LeakyReLU": ((f(B, C, H, H),), {"act_type": "leaky"}),
        # elementwise / broadcast
        "broadcast_add": ((f(B, C, H, H), f(1, C, 1, 1)), {}),
        "broadcast_mul": ((f(B, C, H, H), f(1, C, 1, 1)), {}),
        "elemwise_add": ((f(B, C, H, H), f(B, C, H, H)), {}),
        "exp": ((f(B, C, H, H),), {}),
        "sqrt": ((f(B, C, H, H) + 1.0,), {}),
        "clip": ((f(B, C, H, H),), {"a_min": 0.1, "a_max": 0.9}),
        # reductions / shape
        "sum": ((f(B, C, H, H),), {"axis": (0, 2, 3)}),
        "mean": ((f(B, C, H, H),), {"axis": (0, 2, 3)}),
        "max": ((f(B, C, H, H),), {"axis": (2, 3)}),
        "argsort": ((f(B, N[0]),), {"axis": -1}),
        "transpose": ((f(B, C, H, H),), {"axes": (0, 2, 3, 1)}),
        "Reshape": ((f(B, C, H, H),), {"shape": (B, C * H * H)}),
        "concat": ((f(B, C, H, H), f(B, C, H, H)), {"dim": 1}),
        "slice": ((f(B, C, H, H),),
                  {"begin": (0, 0, 1, 1), "end": (B, C, H - 1, H - 1)}),
        # indexing / embedding
        "take": ((f(N[1], U), r.randint(0, N[1], (B, S)).astype("int32")),
                 {}),
        "Embedding": ((r.randint(0, N[1], (B, S)).astype("int32"),
                       f(N[1], U)),
                      {"input_dim": N[1], "output_dim": U}),
        "one_hot": ((r.randint(0, N[0], (B * 8,)).astype("int32"),),
                    {"depth": N[0]}),
        "gather_nd": ((f(N[0], N[0]),
                       r.randint(0, N[0], (2, 64)).astype("int32")), {}),
        # pooling
        "Pooling": ((f(B, C, H, H),),
                    {"kernel": (2, 2), "stride": (2, 2), "pool_type": "max"}),
        # loss-ish
        "smooth_l1": ((f(B, N[0]),), {"scalar": 1.0}),
        "SoftmaxOutput": ((f(B, N[0]),
                           r.randint(0, N[0], (B,)).astype("float32")), {}),
        # optimizer updates (fwd only — not differentiable)
        "sgd_mom_update": ((f(N[1], N[0]), f(N[1], N[0]), f(N[1], N[0])),
                           {"lr": 0.1, "momentum": 0.9, "wd": 1e-4}),
        "adam_update": ((f(N[1], N[0]), f(N[1], N[0]), f(N[1], N[0]),
                         f(N[1], N[0])),
                        {"lr": 1e-3, "beta1": 0.9, "beta2": 0.999,
                         "epsilon": 1e-8, "wd": 0.0}),
        # vision / detection
        "BilinearResize2D": ((f(B, C, H, H),),
                             {"height": H * 2, "width": H * 2}),
        "box_iou": ((f(256, 4), f(256, 4)), {"format": "corner"}),
        "box_nms": ((np.concatenate(
            [r.rand(1, 512, 1), r.rand(1, 512, 1),
             np.sort(r.rand(1, 512, 4), -1)], -1).astype(np.float32),),
            {"overlap_thresh": 0.5, "topk": 100}),
        # ---- hot-family widening (round-4 verdict item #4) ----
        # Convolution variants: the ResNet bottleneck trio (1x1 project,
        # stride-2 downsample) + depthwise grouping
        "Convolution@1x1": ((f(B, C * 2, H // 2, H // 2),
                             f(C * 2, C * 2, 1, 1)),
                            {"kernel": (1, 1), "num_filter": C * 2,
                             "no_bias": True}),
        "Convolution@s2": ((f(B, C, H, H), f(C * 2, C, 3, 3)),
                           {"kernel": (3, 3), "stride": (2, 2),
                            "pad": (1, 1), "num_filter": C * 2,
                            "no_bias": True}),
        "Convolution@dw": ((f(B, C, H, H), f(C, 1, 3, 3)),
                           {"kernel": (3, 3), "pad": (1, 1),
                            "num_filter": C, "num_group": C,
                            "no_bias": True}),
        # fused RNN op (scan-based lstm/gru) on a BERT-ish shape
        "RNN@lstm": ((f(S, B // 2, U // 2),
                      f(_rnn_psize("lstm", U // 2, U // 2, 1, False))),
                     {"state_size": U // 2, "num_layers": 1,
                      "mode": "lstm"}),
        "RNN@gru": ((f(S, B // 2, U // 2),
                     f(_rnn_psize("gru", U // 2, U // 2, 1, False))),
                    {"state_size": U // 2, "num_layers": 1,
                     "mode": "gru"}),
        # fused attention (the Pallas kernel on TPU, XLA fallback on CPU)
        "dot_product_attention": ((f(B // 4, S, U), f(B // 4, S, U),
                                   f(B // 4, S, U),
                                   np.ones((B // 4, S), np.float32)),
                                  {"num_heads": U // 64}),
        "dot_product_attention@causal": (
            (f(B // 4, S, U), f(B // 4, S, U), f(B // 4, S, U),
             np.ones((B // 4, S), np.float32)),
            {"num_heads": U // 64, "causal": True}),
        # fused Conv+BN+ReLU Pallas unit (XLA fallback on CPU) — NHWC
        "FusedConvUnit": ((f(B, H, H, C), f(C, C, 3, 3), f(C) + 0.5,
                           f(C), f(C)),
                          {"kernel": (3, 3), "pad": (1, 1),
                           "act_in": True, "want_stats": True}),
        # remaining optimizer hot path
        "lamb_update_phase1": ((f(N[1], N[0]), f(N[1], N[0]),
                                f(N[1], N[0]), f(N[1], N[0])),
                               {"beta1": 0.9, "beta2": 0.999,
                                "epsilon": 1e-6, "wd": 0.01, "t": 1}),
        "multi_sgd_update": ((f(N[0], N[0]), f(N[0], N[0])),
                             {"lrs": (0.1,), "wds": (1e-4,),
                              "num_weights": 1}),
        # second widening pass: masking, layout, more indexing/reduction
        # shapes the model zoo actually hits (Dropout is excluded: the
        # raw op takes a key the frontend threads — not harness-callable)
        "where": ((f(B, C, H, H), f(B, C, H, H), f(B, C, H, H)), {}),
        "tile": ((f(B, S),), {"reps": (1, 4)}),
        "SequenceMask": ((f(S, B, U),
                          (r.rand(B) * S).astype(np.float32)),
                         {"use_sequence_length": True, "value": 0.0}),
        "SwapAxis": ((f(B, S, U),), {"dim1": 0, "dim2": 1}),
        "pick": ((f(B, N[0]),
                  r.randint(0, N[0], (B,)).astype("float32")), {}),
        "topk": ((f(B, N[0]),), {"k": 5, "ret_typ": "value"}),
        "norm": ((f(B, C, H, H),), {"ord": 2}),
        "cumsum": ((f(B, N[0]),), {"axis": 1}),
        "sgd_update": ((f(N[1], N[0]), f(N[1], N[0])),
                       {"lr": 0.1, "wd": 1e-4}),
        "L2Normalization": ((f(B, U),), {"mode": "instance"}),
    }
    return sp


def _rnn_psize(mode, input_size, hidden, num_layers, bidirectional):
    import importlib
    rnn_ops = importlib.import_module("mxnet_tpu.ops.rnn")
    return rnn_ops.rnn_param_size(mode, input_size, hidden, num_layers,
                                  bidirectional)


def _time_call(fn, sync, repeat, number):
    """Median over `repeat` batches of `number` calls, µs/call."""
    best = []
    fn()  # warm (compile/caches)
    sync()
    for _ in range(repeat):
        t0 = time.perf_counter()
        for _ in range(number):
            out = fn()
        sync(out)
        best.append((time.perf_counter() - t0) / number)
    best.sort()
    return best[len(best) // 2] * 1e6


def compare(current, against_path, fail_over, floor_us=50.0,
            min_was_us=50.0, expect_all_baseline_rows=True):
    """Regression gate: every row in `against` that also ran now, same
    backend and shape, must not have slowed by more than `fail_over`
    (fraction) in its jit columns.

    Noise handling, calibrated against two same-code baselines on the
    1-core dev box (tools/opperf round-5): sub-50µs timings swing 2-3x
    run to run, so rows with a baseline under `min_was_us` are skipped
    and a regression must clear BOTH an absolute `floor_us` delta and
    the relative threshold.  With (50µs, 50µs, 2x) the gate flags zero
    false positives on identical code while still watching every
    MXU-scale op; tighter thresholds only make sense on an idle
    accelerator host.  Returns (regressions, compared_count)."""
    with open(against_path) as f:
        base = json.load(f)
    if base.get("backend") != current["backend"]:
        return [{"note": f"backend mismatch ({base.get('backend')} vs "
                 f"{current['backend']}) — comparison skipped"}], 0
    base_rows = {(r["op"], r.get("shape")): r for r in base["rows"]}
    regressions, compared = [], 0
    for row in current["rows"]:
        b = base_rows.get((row["op"], row.get("shape")))
        if b is None:
            continue
        for col in ("jit_fwd_us", "jit_bwd_us"):
            was, now = b.get(col), row.get(col)
            if not was or was < min_was_us:
                continue
            compared += 1
            if not now:
                # baseline-present / now-missing: the op regressed from
                # working to failing-to-compile-or-run — the worst kind
                # of regression, never a skip (ADVICE round 5)
                regressions.append(
                    {"op": row["op"], "col": col, "was_us": was,
                     "now_us": None,
                     "note": "timing present in baseline but missing "
                             "now (op no longer compiles/runs?)"})
                continue
            if now - was > floor_us and now > was * (1.0 + fail_over):
                regressions.append(
                    {"op": row["op"], "col": col, "was_us": was,
                     "now_us": now, "ratio": round(now / was, 2)})
    if expect_all_baseline_rows:
        # the complement of the loop above: a baseline op whose ROW is
        # entirely absent from the current sweep (spec dropped, sweep
        # crashed before reaching it) is the same working-to-not-
        # running-at-all class as a missing column — never a skip.
        # row_missing=True exempts these from the retry-confirm pass,
        # which cannot re-measure an op that produced no row.
        cur_keys = {(r["op"], r.get("shape")) for r in current["rows"]}
        for bkey, b in base_rows.items():
            if bkey in cur_keys:
                continue
            for col in ("jit_fwd_us", "jit_bwd_us"):
                was = b.get(col)
                if not was or was < min_was_us:
                    continue
                regressions.append(
                    {"op": b["op"], "col": col, "was_us": was,
                     "now_us": None, "row_missing": True,
                     "note": "row present in baseline but absent from "
                             "the current sweep (op dropped or no "
                             "longer runs)"})
    return regressions, compared




def run_rows(names, specs, args, backend, quiet=False):
    """Measure one row per spec name (the shared sweep body, also used
    by the retry-confirm pass with a subset of names)."""
    import numpy as np  # noqa: F401  (specs were built from the caller)
    import jax
    import jax.numpy as jnp
    import mxnet_tpu as mx
    from mxnet_tpu.ops.registry import get_op

    rows = []
    for name in names:
        if name not in specs:
            print(f"# no spec for {name}", file=sys.stderr)
            continue
        arrs, attrs = specs[name]
        # spec keys may carry an '@variant' suffix (e.g. Convolution@1x1)
        # naming a shape/attr configuration of the same registry op
        op_name = name.split("@")[0]
        op = get_op(op_name)
        jarrs = [jnp.asarray(a) for a in arrs]
        nds = [mx.nd.array(a) for a in arrs]

        def sync(out=None):
            if out is not None:
                jax.block_until_ready(out)

        row = {"op": name, "backend": backend,
               "shape": "x".join(str(a.shape) for a in arrs)}
        # eager (imperative NDArray dispatch; wait_to_read = CS1 sync)
        ndout = [None]

        # private attrs (_train, ...) are supplied by the nd wrapper
        # itself on the eager path
        eager_attrs = {k: v for k, v in attrs.items()
                       if not k.startswith("_")}

        def eager():
            o = getattr(mx.nd, op_name)(*nds, **eager_attrs)
            ndout[0] = o[0] if isinstance(o, (list, tuple)) else o
            return ndout[0]

        row["eager_us"] = round(_time_call(
            lambda: eager(), lambda o=None: ndout[0].wait_to_read(),
            args.repeat, args.number), 1)

        jfn = jax.jit(lambda *xs: op.fn(*xs, **attrs))
        try:
            row["jit_fwd_us"] = round(_time_call(
                lambda: jfn(*jarrs), sync, args.repeat, args.number), 1)
        except Exception as e:  # keep the row: a None column is the
            # signal the regression gate reports, a crashed sweep is a
            # silent skip of every later op
            row["jit_fwd_us"] = None
            row["fwd_note"] = str(e).splitlines()[0][:80]

        if row["jit_fwd_us"] is not None and op.differentiable:
            def scalar_fn(*xs):
                o = op.fn(*xs, **attrs)
                o = o[0] if isinstance(o, (list, tuple)) else o
                return jnp.sum(o.astype(jnp.float32))

            diff_idx = [i for i, a in enumerate(jarrs)
                        if a.dtype.kind == "f"]
            gfn = jax.jit(jax.grad(scalar_fn, argnums=tuple(diff_idx))) \
                if diff_idx else None
            if gfn is not None:
                try:
                    row["jit_bwd_us"] = round(_time_call(
                        lambda: gfn(*jarrs), sync, args.repeat,
                        args.number), 1)
                except Exception as e:  # non-diff attr combos
                    row["jit_bwd_us"] = None
                    row["bwd_note"] = str(e).splitlines()[0][:80]
        rows.append(row)
        if not quiet:
            print(json.dumps(row))
    return rows




def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ops", default=None,
                    help="comma-separated subset (default: all covered)")
    ap.add_argument("--repeat", type=int, default=5)
    ap.add_argument("--number", type=int, default=10)
    ap.add_argument("--large", action="store_true",
                    help="accelerator-scale shapes (auto on non-CPU)")
    ap.add_argument("--out", default=None)
    ap.add_argument("--against", default=None,
                    help="baseline OPPERF json: exit 1 if any op's jit "
                         "column regressed past --fail-over")
    ap.add_argument("--fail-over", type=float, default=1.0,
                    help="allowed slowdown fraction vs --against "
                         "(default 1.0 = 2x; sub-2x deltas are timer "
                         "noise on the 1-core dev box)")
    ap.add_argument("--no-retry", action="store_true",
                    help="skip the retry-confirm pass on flagged ops "
                         "(a regression is normally only reported if "
                         "it reproduces in a targeted re-measure)")
    args = ap.parse_args()

    import numpy as np
    import jax

    backend = jax.default_backend()
    large = args.large or backend != "cpu"
    specs = _specs(np, large)
    names = (args.ops.split(",") if args.ops else sorted(specs))

    rows = run_rows(names, specs, args, backend)

    artifact = {"when": time.strftime("%Y-%m-%d %H:%M:%S"),
                "backend": backend, "large_shapes": large,
                "repeat": args.repeat, "number": args.number,
                "rows": rows}
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(artifact, fh, indent=1)
    if args.against:
        # --ops runs a deliberate subset: absent baseline rows are then
        # expected, not a regression signal
        regressions, compared = compare(
            artifact, args.against, args.fail_over,
            expect_all_baseline_rows=args.ops is None)
        flagged = sorted({r["op"] for r in regressions if "op" in r})
        retried = []
        if flagged and not args.no_retry:
            # retry-confirm: a concurrent process (another build step, a
            # tunnel probe's jax import) can slow a whole stretch of the
            # sweep 2-3x on this 1-core box.  Re-measure ONLY the
            # flagged ops; transient contention clears, a real
            # regression persists in both measurements.
            retried = flagged
            retry_rows = run_rows([n for n in names if n in flagged],
                                  specs, args, backend, quiet=True)
            retry_art = dict(artifact, rows=retry_rows)
            retry_reg, _ = compare(retry_art, args.against,
                                   args.fail_over,
                                   expect_all_baseline_rows=False)
            # confirm on (op, COLUMN): fresh noise tripping a different
            # column of the same op must not rescue the original flag.
            # row_missing flags stand as-is: an op that produced no row
            # cannot be re-measured, so the retry cannot clear it.
            confirmed = {(r["op"], r["col"]) for r in retry_reg
                         if "op" in r}
            regressions = [r for r in regressions
                           if "op" not in r
                           or r.get("row_missing")
                           or (r["op"], r["col"]) in confirmed]
        print(json.dumps({"against": args.against, "compared": compared,
                          "fail_over": args.fail_over,
                          "retried": retried,
                          "regressions": regressions}))
        if any("op" in r for r in regressions):
            return 1
        if compared == 0:
            # fail CLOSED: a backend mismatch or zero overlapping rows
            # means the gate checked nothing — a silent no-op here would
            # let real regressions ship while the nightly stays green
            print(json.dumps({"error": "regression gate compared 0 "
                              "columns (backend mismatch or disjoint "
                              "row keys) — regenerate the baseline on "
                              "this backend"}))
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
