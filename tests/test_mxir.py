"""mxir StableHLO program auditor (ISSUE 19): per-rule known-answer
fixture pairs, parser robustness over compile-cache payloads (a bad
entry is a ``parse_skipped``, never a crash), the offline CLI, and the
runtime hook at the executable-cache insert (opt-in, near-zero when
off, findings via metrics + MXIR report — never a broken compile)."""
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.analysis import ir as mxir
from mxnet_tpu.compile_cache import audit as cc_audit
from mxnet_tpu.compile_cache.store import DiskStore
from mxnet_tpu.gluon.parameter import Parameter
from mxnet_tpu.gluon.trainer import Trainer
from mxnet_tpu.ndarray.ndarray import array as nd_array
from mxnet_tpu.telemetry import instruments as _ins

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_CLI = os.path.join(_REPO, "tools", "mxir.py")


# ---------------------------------------------------------------------------
# rule known answers: every IR rule ships a seeded/clean fixture pair
# ---------------------------------------------------------------------------

class TestFixturePairs:
    @pytest.mark.parametrize("rid", sorted(mxir.FIXTURES))
    def test_seeded_fixture_fires_exactly_once(self, rid):
        fx = mxir.FIXTURES[rid]
        vs = mxir.audit_module(fx["bad"], site=f"fixture:{rid}",
                               **fx.get("kwargs", {}))
        assert [v.rule for v in vs] == [rid], \
            f"{rid} seeded fixture: {[f'{v.rule}: {v.message}' for v in vs]}"

    @pytest.mark.parametrize("rid", sorted(mxir.FIXTURES))
    def test_clean_fixture_is_silent(self, rid):
        fx = mxir.FIXTURES[rid]
        vs = mxir.audit_module(fx["clean"], site=f"fixture:{rid}",
                               **fx.get("kwargs", {}))
        assert vs == [], \
            f"{rid} clean fixture: {[f'{v.rule}: {v.message}' for v in vs]}"

    def test_every_ir_rule_has_a_fixture_pair(self):
        assert set(mxir.FIXTURES) == set(mxir.IR_RULE_IDS)


# ---------------------------------------------------------------------------
# parser robustness: real lowerings parse, garbage degrades gracefully
# ---------------------------------------------------------------------------

class TestParser:
    def test_real_jit_lowering_parses(self):
        import jax
        import jax.numpy as jnp

        text = jax.jit(lambda x: jnp.tanh(x) * 2.0).lower(
            jnp.zeros((8, 4), jnp.float32)).as_text()
        module = mxir.parse_module(text)
        assert module.main is not None
        assert module.main.ops
        assert module.main.args[0].type.shape == (8, 4)

    @pytest.mark.parametrize("text", [
        "", "not stablehlo at all", "module {", "func.func @main",
        "module @m attributes {mhlo.num_partitions = } {}",
    ])
    def test_garbage_raises_irparseerror_not_random(self, text):
        with pytest.raises((mxir.IrParseError, ValueError)):
            mxir.parse_module(text)

    def test_parse_error_becomes_parse_skipped_audit(self):
        a = mxir.ProgramAudit(site="s", parse_error="boom")
        assert a.parse_skipped
        doc = mxir.render_ir_json([a])
        assert doc["counts"]["parse_skipped"] == 1
        assert doc["counts"]["violations"] == 0


# ---------------------------------------------------------------------------
# offline CLI over a compile-cache directory
# ---------------------------------------------------------------------------

class TestOfflineCli:
    def _cache_dir(self, tmp_path, module_text, site="test.site"):
        d = tmp_path / "cc"
        d.mkdir()
        store = DiskStore(str(d))
        digest = "d" * 16
        store.store(digest, {"tier": "stablehlo", "site": site,
                             "digest": digest}, module_text.encode())
        # a non-stablehlo tier (no module text) must be skipped silently
        store.store("e" * 16, {"tier": "exec", "site": site,
                               "digest": "e" * 16}, b"\x00opaque")
        # a corrupt entry must count as parse_skipped, never crash
        (d / "deadbeef.mxcc").write_bytes(b"GARBAGE\x00\x01")
        return str(d)

    def _run(self, *args):
        return subprocess.run(
            [sys.executable, _CLI, *args], capture_output=True,
            text=True, timeout=120, cwd=_REPO)

    def test_clean_cache_exits_zero_and_skips_garbage(self, tmp_path):
        d = self._cache_dir(tmp_path, mxir.FIXTURES["MX015"]["clean"])
        p = self._run(d, "--json")
        assert p.returncode == 0, p.stdout + p.stderr
        doc = json.loads(p.stdout)
        assert doc["counts"]["violations"] == 0
        assert doc["counts"]["parse_skipped"] == 1  # the corrupt entry
        assert doc["counts"]["alias_skipped"] == 1  # the exec-tier entry
        assert any(pr["site"] == "test.site" for pr in doc["programs"])

    def test_seeded_cache_fails_with_findings(self, tmp_path):
        d = self._cache_dir(tmp_path, mxir.FIXTURES["MX015"]["bad"])
        p = self._run(d, "--json", "--repl-bytes", "1024")
        assert p.returncode == 1, p.stdout + p.stderr
        doc = json.loads(p.stdout)
        assert doc["per_rule"].get("MX015", 0) >= 1

    def test_single_module_file_and_out(self, tmp_path):
        f = tmp_path / "mod.mlir"
        f.write_text(mxir.FIXTURES["MX017"]["bad"])
        out = tmp_path / "MXIR.json"
        p = self._run(str(f), "--out", str(out))
        assert p.returncode == 1
        doc = json.loads(out.read_text())
        assert doc["per_rule"].get("MX017", 0) == 1


# ---------------------------------------------------------------------------
# runtime hook: audit at the executable-cache insert
# ---------------------------------------------------------------------------

def _fused_trainer(shapes, seed=7):
    rng = np.random.RandomState(seed)
    params = []
    for i, shp in enumerate(shapes):
        p = Parameter(f"irw{i}", shape=shp, dtype="float32")
        p.initialize(ctx=[mx.cpu()])
        p.set_data(nd_array(rng.randn(*shp).astype("float32")))
        params.append(p)
    t = Trainer(params, "sgd", {"momentum": 0.9}, fuse_step=True)
    return t, params


def _grads(params, step):
    rng = np.random.RandomState(100 + step)
    for p in params:
        g = rng.randn(*p.shape).astype("float32")
        for gnd in p.list_grad():
            gnd._data = nd_array(g, ctx=gnd.ctx).data


class TestRuntimeHook:
    def test_fused_compile_is_audited_clean(self, tmp_path, monkeypatch):
        out = tmp_path / "MXIR.json"
        monkeypatch.setenv("MXNET_IR_AUDIT", "1")
        monkeypatch.setenv("MXNET_IR_OUT", str(out))
        cc_audit.reset()
        # odd shapes so no earlier test already populated this
        # executable-cache slot (the hook runs at INSERT, not lookup)
        t, params = _fused_trainer([(5, 3), (13,)])
        for s in range(2):
            _grads(params, s)
            t.step(1)
        sites = [a.site for a in cc_audit.audits()]
        assert any(s.startswith("optimizer.") for s in sites), sites
        for a in cc_audit.audits():
            assert not a.parse_skipped, a.parse_error
            assert a.violations == [], [v.message for v in a.violations]
            assert a.wire is not None and a.wire["total"] >= 0
        doc = json.loads(out.read_text())
        assert doc["ok"] and doc["counts"]["programs"] >= 1

    def test_bad_program_never_breaks_the_compile(self, monkeypatch):
        monkeypatch.setenv("MXNET_IR_AUDIT", "1")
        cc_audit.reset()
        a = cc_audit.maybe_audit("test.garbage", lambda: "not stablehlo")
        assert a is not None and a.parse_skipped
        assert cc_audit.last_report()["counts"]["parse_skipped"] == 1

    def test_violation_increments_counter(self, monkeypatch):
        monkeypatch.setenv("MXNET_IR_AUDIT", "1")
        monkeypatch.setenv("MXNET_IR_REPL_BYTES", "1024")
        cc_audit.reset()
        before = _ins.ir_violations_total("MX015").value
        a = cc_audit.maybe_audit(
            "test.seeded", lambda: mxir.FIXTURES["MX015"]["bad"])
        assert any(v.rule == "MX015" for v in a.violations)
        assert _ins.ir_violations_total("MX015").value > before


class TestAuditOffOverhead:
    def test_off_path_never_materializes_text(self, monkeypatch):
        monkeypatch.delenv("MXNET_IR_AUDIT", raising=False)
        calls = []

        def text_fn():
            calls.append(1)
            return ""

        assert cc_audit.maybe_audit("site", text_fn) is None
        assert calls == []

    def test_off_path_is_cheap(self, monkeypatch):
        # the acceptance bound (<=3% of a fused step) is enforced by
        # tools/mxir.py --selftest; here just pin the off path to the
        # one-knob-read order of magnitude on the tier-1 box
        monkeypatch.delenv("MXNET_IR_AUDIT", raising=False)
        fn = lambda: ""  # noqa: E731
        best = float("inf")
        for _ in range(5):
            t0 = time.perf_counter()
            for _ in range(1000):
                cc_audit.maybe_audit("site", fn)
            best = min(best, time.perf_counter() - t0)
        assert best < 0.25, f"1000 disabled audits took {best:.3f}s"
