"""Fused-step perf gate (ref: FUSED_BENCH.json — ISSUE 3).

The strict assertion — fused update >= 1.2x the eager per-parameter
loop at >= 100 parameters on the CPU CI box (the accelerator
expectation is 1.5x+) — belongs in the nightly perf lane, not tier-1:
wall-clock on a loaded shared box is not deterministic.  Tier-1 keeps
the CLI smoke (tests/test_tools_bench.py) and the numeric parity suite
(tests/test_fused_step.py).
"""
import json
import os
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))


def _run(cmd, timeout=600):
    env = dict(os.environ)
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    p = subprocess.run(cmd, capture_output=True, text=True, cwd=_REPO,
                       timeout=timeout, env=env)
    assert p.returncode == 0, p.stdout[-2000:] + p.stderr[-2000:]
    lines = [ln for ln in p.stdout.splitlines() if ln.startswith("{")]
    assert lines, p.stdout[-2000:]
    return [json.loads(ln) for ln in lines]


def test_fused_step_beats_eager_loop(tmp_path):
    """ISSUE 3 gate: at >= 100 parameters the fused path must be >=
    1.2x the eager loop (CPU), with EXACTLY one executable build across
    a schedule that changes the learning rate and the batch size."""
    out = tmp_path / "FUSED_BENCH.json"
    rows = _run([sys.executable, "tools/bench_fused_step.py",
                 "--params", "100", "--steps", "20",
                 "--min-speedup", "1.2", "--out", str(out)])
    report = rows[-1]
    assert report["gate_params"] == 100
    row = report["sizes"]["100"]
    assert row["speedup"] >= 1.2
    assert row["fused_compiles"] == 1
    assert row["eager_ms_per_step"] > 0
    assert row["fused_ms_per_step"] > 0
    assert json.loads(out.read_text()) == report
