"""DynamicBatcher: coalesce concurrent requests into padded, bucketed
batches.

One batcher per (model, version).  Requests for the same *group* —
identical non-batch input shapes/dtypes, identical scalar side-inputs,
identical seed — are concatenated along dim 0, padded up to the next
bucket on the ladder, and launched through the entry's ONE cached
executable for that bucket.  A batch launches when it is full
(`max_batch_size` rows) or when its oldest request has waited
`batch_timeout_ms` (the latency bound); expired deadlines are failed
with `DeadlineExceeded` *before* launch, never silently dropped.

Padding is row-wise zeros and is sliced off the outputs, which is
exactly output-preserving for batch-major programs — the entry's
`coalescable()` check (every output leaf leading dim = the shared
batch) gates coalescing; non-coalescable artifacts are served one
request per launch with exact exported shapes.

Stochastic caveat: the per-launch PRNG key is shared by every row of a
coalesced batch, so a program that actually DRAWS from it (eval-mode
sampling layers) sees draws that depend on its row offset and bucket —
same-seed requests coalesce (the seed is part of the group key) but
are not bitwise-reproducible against a solo call.  Callers needing
exact single-call reproducibility for a stochastic model should give
the request a unique seed, which by construction never shares a launch.
"""
from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from concurrent.futures import Future
from typing import Dict, List, Optional

from .. import profiler as _prof
from ..resilience import retry as _retry
from ..telemetry import instruments as _ins
from ..telemetry import tracing as _tracing
from . import (DeadlineExceeded, ServerClosed, ServingConfig,
               ServingError)

__all__ = ["DynamicBatcher"]


class _Request:
    __slots__ = ("xs", "rows", "seed", "future", "deadline", "enq",
                 "enq_pc", "trace")

    def __init__(self, xs, rows, seed, deadline, trace=None):
        self.xs, self.rows, self.seed = xs, rows, seed
        self.deadline = deadline
        self.future: Future = Future()
        self.enq = time.monotonic()
        # perf_counter twin of enq: span timestamps must share the
        # profiler's clock, monotonic stays the deadline clock
        self.enq_pc = time.perf_counter()
        self.trace = trace  # (trace_id, admission_span_id) or None


class DynamicBatcher:
    """Background-thread batcher over one repository entry."""

    def __init__(self, entry, config: Optional[ServingConfig] = None):
        self._entry = entry
        self._config = config or ServingConfig()
        self._buckets = entry.allowed_buckets(self._config.ladder())
        # an empty ladder (fixed artifact, inconsistent input dims)
        # serves exact-shape one-request launches only: the rows cap
        # is meaningless there, exact shape match is the bound
        self._max_rows = min(self._config.max_batch_size,
                             self._buckets[-1]) if self._buckets \
            else self._config.max_batch_size
        self._timeout_s = self._config.batch_timeout_ms / 1e3
        self._coalesce = entry.coalescable()
        self._fixed = entry.fixed_batch()
        self._specs = entry.input_specs()
        # transient executor failures retry (deadline-aware) under this
        # policy; ServingConfig.execute_retries overrides the env knob
        self._retry_policy = _retry.RetryPolicy(
            max_attempts=self._config.execute_retries) \
            if self._config.execute_retries is not None \
            else _retry.default_policy()
        self._cv = threading.Condition()
        # group key -> FIFO of requests (OrderedDict: oldest group first)
        self._groups: "OrderedDict[tuple, deque]" = OrderedDict()
        self._closing = False
        self._drain = True
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"mx-batcher-{entry.name}-v{entry.version}")
        self._thread.start()

    # ---- submission ---------------------------------------------------

    def submit(self, inputs, seed: int = 0,
               deadline: Optional[float] = None, trace=None) -> Future:
        """Enqueue one request (inputs carry their own leading batch
        dim; most clients send 1 row).  Returns a Future resolving to
        the model's documented output structure (NDArray leaves).
        `trace` is the request's (trace_id, admission_span_id) pair —
        queue-wait/execute spans on the batcher thread link back to it."""
        xs, rows = self._validate(inputs)
        req = _Request(xs, rows, int(seed), deadline, trace=trace)
        key = self._group_key(xs, req.seed)
        with self._cv:
            if self._closing:
                raise ServerClosed(
                    f"model {self._entry.name!r}: server is shutting "
                    f"down, not accepting new requests")
            self._groups.setdefault(key, deque()).append(req)
            if trace is not None:
                # flow arrow (enqueue here -> batch execution over
                # there), emitted BEFORE the batcher thread can wake
                # and emit the matching flow_end — end-before-start
                # arrows get dropped by trace viewers
                _tracing.flow_start(trace[0])
            self._cv.notify()
        return req.future

    def _validate(self, inputs):
        import jax.numpy as jnp

        from ..ndarray.ndarray import NDArray

        specs = self._specs
        if len(inputs) != len(specs):
            raise ServingError(
                f"model {self._entry.name!r} takes {len(specs)} "
                f"inputs, got {len(inputs)}")
        xs, rows = [], None
        for x, w in zip(inputs, specs):
            v = x.data if isinstance(x, NDArray) else jnp.asarray(x)
            want = w["shape"]
            if str(v.dtype) != w["dtype"]:
                raise ServingError(
                    f"input dtype {v.dtype} != exported {w['dtype']}")
            if len(want) == 0:
                if v.ndim != 0:
                    raise ServingError(
                        f"input shape {list(v.shape)} != exported "
                        f"scalar")
            else:
                got = list(v.shape)
                if len(got) != len(want) or \
                        any(ws is not None and i > 0 and gs != ws
                            for i, (gs, ws) in enumerate(zip(got, want))) \
                        or (want[0] is not None and not self._coalesce
                            and got[0] != want[0]):
                    raise ServingError(
                        f"input shape {got} != exported {want} "
                        f"(dim 0 = rows; other dims are fixed)")
                if want[0] is None or self._coalesce:
                    # exact-shape inputs of a non-coalescable fixed
                    # artifact may legitimately disagree on dim 0
                    # (e.g. a lookup table beside the data batch)
                    if rows is None:
                        rows = got[0]
                    elif got[0] != rows:
                        raise ServingError(
                            f"all batchable inputs must share the row "
                            f"count, got {rows} and {got[0]}")
            xs.append(v)
        rows = 1 if rows is None else rows
        if rows < 1:
            raise ServingError("request must carry at least one row")
        if rows > self._max_rows:
            raise ServingError(
                f"request rows {rows} > max_batch_size "
                f"{self._max_rows}; split the request")
        return xs, rows

    def _group_key(self, xs, seed):
        parts: List[tuple] = [("seed", seed)]
        for v, w in zip(xs, self._specs):
            if len(w["shape"]) == 0:
                # scalar side-inputs must match bitwise to share a
                # launch (they are passed once per batch)
                parts.append(("s", str(v.dtype), v.tobytes()))
            else:
                parts.append(("b", str(v.dtype), tuple(v.shape[1:])))
        return tuple(parts)

    # ---- batching loop ------------------------------------------------

    def _loop(self):
        while True:
            expired: List[_Request] = []
            batch = None
            with self._cv:
                while not self._groups and not self._closing:
                    self._cv.wait()
                if self._closing and not self._groups:
                    return
                now = time.monotonic()
                expired = self._pop_expired_locked(now)
                batch = self._take_due_locked(now)
                if batch is None and not expired:
                    wake = self._next_event_locked()
                    if wake is not None:
                        self._cv.wait(timeout=max(wake - now, 1e-4))
            for r in expired:
                try:
                    r.future.set_exception(DeadlineExceeded(
                        f"model {self._entry.name!r}: deadline expired "
                        f"after {(time.monotonic() - r.enq) * 1e3:.1f}ms "
                        f"in queue"))
                except Exception:
                    continue  # beaten by a concurrent Future.cancel()
                self._entry.metrics.bump("deadline_expired")
            if batch is not None:
                self._run_batch(*batch)

    def _pop_expired_locked(self, now) -> List[_Request]:
        out: List[_Request] = []
        for key in list(self._groups):
            q = self._groups[key]
            alive = deque(r for r in q
                          if r.deadline is None or r.deadline > now)
            out.extend(r for r in q
                       if r.deadline is not None and r.deadline <= now)
            if alive:
                self._groups[key] = alive
            else:
                del self._groups[key]
        return out

    def _take_due_locked(self, now):
        for key in list(self._groups):
            q = self._groups[key]
            full = sum(r.rows for r in q) >= self._max_rows
            timed_out = q and (now - q[0].enq) >= self._timeout_s
            # one request per launch anyway -> nothing to wait for
            if not (full or timed_out or self._closing
                    or not self._coalesce):
                continue
            take, taken_rows = [], 0
            while q and taken_rows + q[0].rows <= self._max_rows:
                if not self._coalesce and take:
                    break  # one request per launch
                r = q.popleft()
                # transition PENDING -> RUNNING; once in a launch the
                # future can no longer be cancelled, so result/exception
                # delivery below never hits InvalidStateError.  False
                # means the client cancelled while queued: drop the
                # request, don't launch its rows.
                if not r.future.set_running_or_notify_cancel():
                    continue
                take.append(r)
                taken_rows += r.rows
            if not q:
                del self._groups[key]
            if take:
                return key, take, taken_rows
        return None

    def _next_event_locked(self) -> Optional[float]:
        """Earliest future instant the loop must act on: a group's
        flush-due time or a request deadline."""
        t = None
        for q in self._groups.values():
            cand = q[0].enq + self._timeout_s
            t = cand if t is None else min(t, cand)
            for r in q:
                if r.deadline is not None:
                    t = r.deadline if t is None else min(t, r.deadline)
        return t

    def _trace_batch_start(self, reqs: List[_Request], rows: int):
        """Emit per-request queue-wait spans + flow ends, and open the
        batch-assembly span.  The batch's spans ride the FIRST traced
        request's trace id (its `traces` arg lists every member) — a
        single-request batch therefore shows one trace id end-to-end:
        admission → queue-wait → batch-assembly → execute → respond."""
        # spans only exist in a capture: with telemetry on but no
        # profiler running nothing here would record, so skip the
        # whole machinery (metrics are handled by ModelMetrics)
        if not _prof._running:
            return None
        now = time.perf_counter()
        primary = None
        member_traces = []
        for r in reqs:
            if r.trace is None:
                continue
            member_traces.append(r.trace[0])
            if primary is None:
                primary = r.trace
            _tracing.record_complete(
                "queue-wait", "serving", r.enq_pc, now - r.enq_pc,
                trace_id=r.trace[0], parent_id=r.trace[1])
            _tracing.flow_end(r.trace[0])
        return _tracing.Span(
            "batch-assembly", "serving",
            trace_id=primary[0] if primary else None,
            parent_id=primary[1] if primary else None,
            args={"rows": rows, "traces": member_traces})

    def _run_batch(self, key, reqs: List[_Request], rows: int):
        import jax.numpy as jnp

        from ..context import current_context
        from ..ndarray.ndarray import NDArray

        entry = self._entry
        m = entry.metrics
        phase = self._trace_batch_start(reqs, rows)
        try:
            # non-coalescable programs (outputs not batch-major) run at
            # the EXACT exported/request shape: padding rows would leak
            # into reduced outputs (a scalar mean over 4 rows != over
            # 3).  For a fixed-shape artifact that exact shape is the
            # exported batch, not the request's logical row count.
            bucket = next(b for b in self._buckets if b >= rows) \
                if self._coalesce else (self._fixed or rows)
            xs = []
            for i, w in enumerate(self._specs):
                if len(w["shape"]) == 0:
                    xs.append(reqs[0].xs[i])
                    continue
                cols = [r.xs[i] for r in reqs]
                v = cols[0] if len(cols) == 1 else \
                    jnp.concatenate(cols, axis=0)
                if self._coalesce and bucket > rows:
                    pad = jnp.zeros((bucket - rows,) + tuple(v.shape[1:]),
                                    dtype=v.dtype)
                    v = jnp.concatenate([v, pad], axis=0)
                xs.append(v)
            if phase is not None:
                tr, par = phase.trace_id, phase.parent_id
                phase.finish()
                phase = _tracing.Span("execute", "serving", trace_id=tr,
                                      parent_id=par,
                                      args={"bucket": bucket})
            leaves = self._execute_resilient(bucket, xs, reqs)
            m.bump("batches")
            m.bump("batched_rows", rows)
            m.bump("padded_rows", bucket)
            _ins.serving_occupancy(entry.name, entry.version).set(
                rows / bucket)
            if phase is not None:
                tr, par = phase.trace_id, phase.parent_id
                phase.finish()
                phase = _tracing.Span("respond", "serving", trace_id=tr,
                                      parent_id=par)
            ctx = current_context()
            off = 0
            for r in reqs:
                if self._coalesce:
                    cut = [NDArray(o[off:off + r.rows], ctx=ctx)
                           for o in leaves]
                else:
                    cut = [NDArray(o, ctx=ctx) for o in leaves]
                off += r.rows
                r.future.set_result(
                    entry.served.decode_outputs(cut))
        except BaseException as e:  # noqa: BLE001 — fail the futures
            for r in reqs:
                if not r.future.done():
                    m.bump("failed")
                    r.future.set_exception(e)
        finally:
            if phase is not None:
                phase.finish()

    def _execute_resilient(self, bucket: int, xs, reqs: List[_Request]):
        """The executor launch under the resilience stack: every
        attempt's outcome feeds the entry's circuit breaker (that's how
        consecutive failures trip it), and a TRANSIENT failure retries
        with backoff while the batch's earliest request deadline allows
        — a blip must cost one retry delay, not fail a whole coalesced
        batch.  Non-transient errors (shape bugs, a poisoned artifact)
        fail immediately; the breaker counts them all the same."""
        entry = self._entry

        def attempt():
            leaves = entry.execute(bucket, xs, seed=reqs[0].seed)
            entry.breaker.record_success()
            return leaves

        policy = self._retry_policy
        deadline = min((r.deadline for r in reqs
                        if r.deadline is not None), default=None)
        try:
            return policy.call(
                attempt, site="serving.execute", deadline=deadline,
                on_failure=lambda e: entry.breaker.record_failure())
        except _retry.RetryExhausted:
            entry.metrics.bump("retries_exhausted")
            raise

    # ---- lifecycle ----------------------------------------------------

    def pending(self) -> int:
        with self._cv:
            return sum(len(q) for q in self._groups.values())

    def close(self, drain: bool = True,
              timeout: Optional[float] = None) -> None:
        """Stop admission.  drain=True completes everything already
        queued (in-flight batches always finish); drain=False fails
        queued requests with ServerClosed.

        `timeout` is a HARD drain deadline: if the batcher thread is
        still busy past it (a wedged executor), every request still
        QUEUED is failed with ServerClosed and close() returns — the
        in-flight batch keeps its daemon thread, but shutdown never
        hangs on it."""
        with self._cv:
            if self._closing:
                self._cv.notify_all()
            self._closing = True
            dropped: List[_Request] = []
            if not drain:
                for q in self._groups.values():
                    dropped.extend(q)
                self._groups.clear()
            self._cv.notify_all()
        self._fail_requests(dropped, "server shut down before this "
                            "request ran")
        self._thread.join(timeout)
        if self._thread.is_alive() and drain:
            # drain deadline blown: a batch is wedged in the executor.
            # Everything still queued can never run before the process
            # exits — fail it loudly now instead of hanging forever.
            with self._cv:
                stuck: List[_Request] = []
                for q in self._groups.values():
                    stuck.extend(q)
                self._groups.clear()
                self._cv.notify_all()
            self._entry.metrics.bump("drain_timeouts")
            self._fail_requests(
                stuck, f"drain deadline ({timeout:.1f}s) expired with "
                f"a batch still executing; this queued request was "
                f"abandoned")

    def _fail_requests(self, reqs: List[_Request], why: str) -> None:
        for r in reqs:
            try:
                r.future.set_exception(ServerClosed(
                    f"model {self._entry.name!r}: {why}"))
            except Exception:
                pass  # already done or concurrently cancelled
