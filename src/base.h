// Native-layer foundations: CHECK/LOG + thread-local error ring.
//
// TPU-native counterpart of dmlc-core's logging surface
// (ref: 3rdparty/dmlc-core include/dmlc/logging.h; src/c_api error ring
// MXGetLastError).  Errors thrown as NativeError are caught at the C ABI
// boundary and surfaced to Python via MXGetLastError (same contract as the
// reference's MXNetError propagation).
#pragma once

#include <cstdio>
#include <stdexcept>
#include <string>

namespace mxt {

class NativeError : public std::runtime_error {
 public:
  explicit NativeError(const std::string& msg) : std::runtime_error(msg) {}
};

// thread-local last-error storage for the C ABI
std::string& LastError();

#define MXT_CHECK(cond)                                                    \
  if (!(cond))                                                             \
  throw ::mxt::NativeError(std::string("Check failed: " #cond " at ") +    \
                           __FILE__ + ":" + std::to_string(__LINE__))

#define MXT_CHECK_MSG(cond, msg)                                           \
  if (!(cond)) throw ::mxt::NativeError(std::string(msg))

// wrap a C ABI body: catches exceptions, stores message, returns -1/0
#define MXT_API_BEGIN() try {
#define MXT_API_END()                                                      \
  }                                                                        \
  catch (const std::exception& e) {                                        \
    ::mxt::LastError() = e.what();                                         \
    return -1;                                                             \
  }                                                                        \
  return 0;

}  // namespace mxt
