"""nd.random namespace (ref: python/mxnet/ndarray/random.py)."""
from __future__ import annotations

from .. import random as _random
from ..ops.registry import invoke

__all__ = ["uniform", "normal", "randn", "randint", "gamma", "exponential",
           "poisson", "negative_binomial", "multinomial", "shuffle",
           "bernoulli", "gumbel", "laplace", "seed"]

seed = _random.seed


def _sample(op, shape, dtype, ctx, out=None, **params):
    """One implementation of the sampler contract for every wrapper,
    including the reference's in-place `out=` semantics: with `out`
    given, shape/dtype/ctx default from it (and must agree when also
    passed explicitly), the sample lands on out's device, and `out` is
    filled and returned."""
    from ..base import MXNetError

    if out is not None:
        if shape is not None and tuple(out.shape) != (
                (shape,) if isinstance(shape, int) else tuple(shape)):
            raise MXNetError(f"out shape {out.shape} != requested {shape}")
        if dtype is not None:
            import numpy as _np

            try:
                same = _np.dtype(dtype) == _np.dtype(out.dtype)
            except TypeError:  # e.g. bfloat16 class spellings
                same = str(out.dtype) == str(dtype)
            if not same:
                raise MXNetError(
                    f"out dtype {out.dtype} != requested {dtype}")
        shape = tuple(out.shape)
        dtype = str(out.dtype)
        ctx = ctx or out.ctx
    if shape is None:
        shape = (1,)
    if isinstance(shape, int):
        shape = (shape,)
    res = invoke(op, _random.next_key(), shape=tuple(shape),
                 dtype=dtype or "float32", **params)
    if ctx is not None:
        res = res.as_in_context(ctx)
    if out is not None:
        out._data = res.data
        return out
    return res


def uniform(low=0.0, high=1.0, shape=None, dtype=None, ctx=None, out=None):
    return _sample("_random_uniform", shape, dtype, ctx, out=out, low=low, high=high)


def normal(loc=0.0, scale=1.0, shape=None, dtype=None, ctx=None, out=None):
    return _sample("_random_normal", shape, dtype, ctx, out=out, loc=loc, scale=scale)


def randn(*shape, dtype=None, ctx=None):
    return normal(0.0, 1.0, shape or (1,), dtype=dtype, ctx=ctx)


def randint(low, high, shape=None, dtype=None, ctx=None, out=None):
    # signature default must stay None: with out= given, dtype defaults
    # FROM out (int64 out works); int32 only when neither is specified
    if dtype is None and out is None:
        dtype = "int32"
    return _sample("_random_randint", shape, dtype, ctx, out=out,
                   low=low, high=high)


def gamma(alpha=1.0, beta=1.0, shape=None, dtype=None, ctx=None, out=None):
    return _sample("_random_gamma", shape, dtype, ctx, out=out, alpha=alpha, beta=beta)


def exponential(scale=1.0, shape=None, dtype=None, ctx=None, out=None):
    return _sample("_random_exponential", shape, dtype, ctx, out=out, lam=1.0 / scale)


def poisson(lam=1.0, shape=None, dtype=None, ctx=None, out=None):
    return _sample("_random_poisson", shape, dtype, ctx, out=out, lam=lam)


def negative_binomial(k=1, p=1.0, shape=None, dtype=None, ctx=None, out=None):
    return _sample("_random_negative_binomial", shape, dtype, ctx, out=out, k=k, p=p)


def gumbel(loc=0.0, scale=1.0, shape=None, dtype=None, ctx=None, out=None):
    return _sample("_random_gumbel", shape, dtype, ctx, out=out, loc=loc, scale=scale)


def laplace(loc=0.0, scale=1.0, shape=None, dtype=None, ctx=None, out=None):
    return _sample("_random_laplace", shape, dtype, ctx, out=out, loc=loc, scale=scale)


def bernoulli(p=0.5, shape=None, dtype=None, ctx=None, out=None):
    return _sample("_random_bernoulli", shape, dtype, ctx, out=out, p=p)


def multinomial(data, shape=(), get_prob=False, dtype="int32", **kw):
    return invoke("_sample_multinomial", _random.next_key(), data,
                  shape=tuple(shape) if not isinstance(shape, int) else (shape,),
                  get_prob=get_prob, dtype=dtype)


def shuffle(data, **kw):
    return invoke("_shuffle", _random.next_key(), data)
