"""Fused attention + BERT tests (BASELINE config 3 plumbing)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd
from mxnet_tpu.gluon import Trainer
from mxnet_tpu.gluon.loss import SoftmaxCrossEntropyLoss
from mxnet_tpu.gluon.model_zoo.bert import (BERTModel, bert_12_768_12,
                                            get_bert_model)
from mxnet_tpu.test_utils import assert_almost_equal, check_numeric_gradient


def _np_attention(q, k, v, mask, scale):
    s = np.einsum("bqd,bkd->bqk", q, k) * scale
    s = np.where(mask[:, None, :] > 0, s, -1e30)
    e = np.exp(s - s.max(-1, keepdims=True))
    p = e / e.sum(-1, keepdims=True)
    return np.einsum("bqk,bkd->bqd", p, v)


def test_attention_matches_numpy():
    rng = np.random.RandomState(0)
    b, h, s, d = 2, 4, 16, 8
    q = rng.randn(b, s, h * d).astype("float32")
    k = rng.randn(b, s, h * d).astype("float32")
    v = rng.randn(b, s, h * d).astype("float32")
    lengths = np.array([16, 9], "float32")
    mask = (np.arange(s)[None, :] < lengths[:, None]).astype("float32")
    got = nd.dot_product_attention(nd.array(q), nd.array(k), nd.array(v),
                                   nd.array(mask), num_heads=h).asnumpy()
    # numpy reference on head-split layout
    def split(x):
        return x.reshape(b, s, h, d).transpose(0, 2, 1, 3).reshape(b * h, s, d)
    ref = _np_attention(split(q), split(k), split(v),
                        np.repeat(mask, h, axis=0), 1.0 / np.sqrt(d))
    ref = ref.reshape(b, h, s, d).transpose(0, 2, 1, 3).reshape(b, s, h * d)
    assert_almost_equal(got, ref, rtol=1e-4, atol=1e-4)


def test_attention_vs_torch():
    torch = pytest.importorskip("torch")
    rng = np.random.RandomState(1)
    b, h, s, d = 2, 2, 8, 4
    q = rng.randn(b, h, s, d).astype("float32")
    k = rng.randn(b, h, s, d).astype("float32")
    v = rng.randn(b, h, s, d).astype("float32")
    got = nd.dot_product_attention(nd.array(q), nd.array(k),
                                   nd.array(v)).asnumpy()
    ref = torch.nn.functional.scaled_dot_product_attention(
        torch.tensor(q), torch.tensor(k), torch.tensor(v)).numpy()
    assert_almost_equal(got, ref, rtol=1e-4, atol=1e-4)


def test_attention_gradients():
    rng = np.random.RandomState(2)
    b, h, s, d = 1, 2, 4, 4
    q = rng.randn(b, h, s, d).astype("float32") * 0.5
    k = rng.randn(b, h, s, d).astype("float32") * 0.5
    v = rng.randn(b, h, s, d).astype("float32") * 0.5
    check_numeric_gradient(
        lambda q_, k_, v_: nd.dot_product_attention(q_, k_, v_),
        [q, k, v], rtol=3e-2, atol=3e-2)


def test_pallas_kernel_interpret_matches_reference(monkeypatch):
    """Validate the Pallas kernel body itself (interpret mode on CPU) —
    covers the q-block grid and the sequence-padding path."""
    import jax.numpy as jnp

    from mxnet_tpu.ops import pallas_attention as pa

    monkeypatch.setenv("MXNET_PALLAS_INTERPRET", "1")
    rng = np.random.RandomState(0)
    for bh, s, d, lens in [(4, 40, 16, [40, 17, 40, 3]),
                           (2, 200, 16, [200, 77])]:
        q = rng.randn(bh, s, d).astype("float32")
        k = rng.randn(bh, s, d).astype("float32")
        v = rng.randn(bh, s, d).astype("float32")
        mask = (np.arange(s)[None, :] <
                np.array(lens)[:, None]).astype("float32")
        got = np.asarray(pa._attention_pallas(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
            jnp.asarray(mask), 0.25))
        ref = np.asarray(pa.dot_product_attention_ref(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
            jnp.asarray(mask), 0.25))
        assert_almost_equal(got, ref, rtol=1e-4, atol=1e-4)


@pytest.fixture(scope="module")
def tiny_bert():
    net = get_bert_model("bert_12_768_12", vocab_size=100, num_layers=2,
                         units=32, hidden_size=64, num_heads=4,
                         max_length=32, dropout=0.1)
    net.initialize(mx.initializer.Normal(0.02), ctx=mx.cpu())
    return net


def test_bert_forward_shapes(tiny_bert):
    net = tiny_bert
    b, s = 2, 12
    tokens = nd.array(np.random.randint(0, 100, (b, s)).astype("float32"))
    segments = nd.zeros((b, s))
    vlen = nd.array([12.0, 7.0])
    seq, pooled = net(tokens, segments, vlen)
    assert seq.shape == (b, s, 32)
    assert pooled.shape == (b, 32)
    mlm = net.decode_mlm(seq)
    assert mlm.shape == (b, s, 100)
    nsp = net.classify_nsp(pooled)
    assert nsp.shape == (b, 2)


def test_bert_padding_invariance(tiny_bert):
    """Positions beyond valid_length must not affect valid positions."""
    net = tiny_bert
    b, s = 1, 10
    rng = np.random.RandomState(0)
    toks = rng.randint(0, 100, (b, s)).astype("float32")
    toks2 = toks.copy()
    toks2[:, 6:] = 99  # scramble the padding region
    vlen = nd.array([6.0])
    seg = nd.zeros((b, s))
    s1, _ = net(nd.array(toks), seg, vlen)
    s2, _ = net(nd.array(toks2), seg, vlen)
    assert_almost_equal(s1.asnumpy()[:, :6], s2.asnumpy()[:, :6], rtol=1e-4,
                        atol=1e-4)


def test_bert_pretrain_step(tiny_bert):
    net = tiny_bert
    loss_fn = SoftmaxCrossEntropyLoss()
    trainer = Trainer(net.collect_params(), "adam", {"learning_rate": 1e-4})
    b, s = 4, 16
    rng = np.random.RandomState(3)
    tokens = nd.array(rng.randint(0, 100, (b, s)).astype("float32"))
    segments = nd.zeros((b, s))
    vlen = nd.array([16.0] * b)
    mlm_labels = nd.array(rng.randint(0, 100, (b, s)).astype("float32"))
    nsp_labels = nd.array(rng.randint(0, 2, (b,)).astype("float32"))

    losses = []
    for _ in range(3):
        with autograd.record():
            seq, pooled = net(tokens, segments, vlen)
            mlm_scores = net.decode_mlm(seq)
            nsp_scores = net.classify_nsp(pooled)
            l_mlm = loss_fn(mlm_scores, mlm_labels).mean()
            l_nsp = loss_fn(nsp_scores, nsp_labels).mean()
            loss = l_mlm + l_nsp
        loss.backward()
        trainer.step(b)
        losses.append(float(loss.asnumpy()))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0], losses


def test_bert_mlm_weights_tied(tiny_bert):
    """decode_mlm projects with the word-embedding matrix (weight tying)."""
    net = tiny_bert
    seq = nd.array(np.random.randn(1, 3, 32).astype("float32"))
    before = net.decode_mlm(seq).asnumpy()
    w = net.word_embed.weight
    w.set_data(w.data() * 2.0)
    after = net.decode_mlm(seq).asnumpy()
    assert not np.allclose(before, after)


def test_bert_hybridize(tiny_bert):
    net = tiny_bert
    b, s = 2, 8
    tokens = nd.array(np.random.randint(0, 100, (b, s)).astype("float32"))
    segments = nd.zeros((b, s))
    vlen = nd.array([8.0, 5.0])
    eager = net(tokens, segments, vlen)[0].asnumpy()
    net.hybridize()
    hybrid = net(tokens, segments, vlen)[0].asnumpy()
    assert_almost_equal(eager, hybrid, rtol=1e-4, atol=1e-4)
    net.hybridize(active=False)


def test_bert_base_constructs():
    net = bert_12_768_12(vocab_size=1000)
    params = net.collect_params()
    n_layers = sum(1 for k in params if "layer11" in k)
    assert n_layers > 0  # 12 encoder layers exist
