"""mxrank cross-rank collective-schedule verification (ISSUE 20):
static divergence rules (MX019 rank-tainted, MX020 data-tainted) with
seeded/clean fixture pairs over the mxflow taint lattice, the runtime
schedule ledger (fingerprint encode/compare, publish/read round-trip,
bounded window, off-switch cost), the watchdog-timeout reclassification
(PeerFailed -> ScheduleDivergence only on fingerprint mismatch), and
the supervisor's job-fatal-no-restart handling of a divergence exit."""
import json
import os
import subprocess
import sys
import textwrap
import time

import pytest

from mxnet_tpu import analysis

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def lint_source(tmp_path, source, enable=None, name="fixture.py"):
    f = tmp_path / name
    f.write_text(textwrap.dedent(source))
    eng = analysis.LintEngine(root=str(tmp_path), enable=enable)
    return eng.run([str(f)])


def rules_hit(violations):
    return sorted({v.rule for v in violations})


# ---------------------------------------------------------------------------
# MX019 — rank-divergent collective schedule
# ---------------------------------------------------------------------------

class TestMX019:
    def test_flags_rank_gated_collective_in_hot_step(self, tmp_path):
        vs = lint_source(tmp_path, """
            from mxnet_tpu.parallel import dist

            class MyTrainer:
                def step(self, grads):
                    if dist.rank() == 0:
                        dist.barrier("ckpt")
                    dist.allreduce_nd(grads)
            """, enable=["MX019"])
        assert rules_hit(vs) == ["MX019"]
        assert "barrier" in vs[0].message

    def test_flags_rank_gated_early_return_skipping_collective(
            self, tmp_path):
        vs = lint_source(tmp_path, """
            from mxnet_tpu.parallel import dist

            class MyTrainer:
                def step(self, grads):
                    if dist.rank() != 0:
                        return
                    dist.allreduce_nd(grads)
            """, enable=["MX019"])
        assert rules_hit(vs) == ["MX019"]

    def test_flags_env_rank_read_as_rank_source(self, tmp_path):
        vs = lint_source(tmp_path, """
            from mxnet_tpu.parallel import dist
            from mxnet_tpu.util import env

            class MyTrainer:
                def step(self, grads):
                    r = env.get_int("MXNET_ELASTIC_RANK")
                    if r == 0:
                        dist.barrier("only-chief")
                    dist.allreduce_nd(grads)
            """, enable=["MX019"])
        assert rules_hit(vs) == ["MX019"]

    def test_flags_rank_divergent_loop_trip_count(self, tmp_path):
        vs = lint_source(tmp_path, """
            from mxnet_tpu.parallel import dist

            class MyTrainer:
                def step(self, grads):
                    for _ in range(dist.rank() + 1):
                        dist.allreduce_nd(grads)
            """, enable=["MX019"])
        assert rules_hit(vs) == ["MX019"]

    def test_clean_rank_gated_noncollective_work(self, tmp_path):
        vs = lint_source(tmp_path, """
            from mxnet_tpu.parallel import dist

            class MyTrainer:
                def step(self, grads):
                    if dist.rank() == 0:
                        print("chief logging")
                    dist.allreduce_nd(grads)
            """, enable=["MX019"])
        assert vs == []

    def test_clean_symmetric_collectives_in_both_branches(self,
                                                          tmp_path):
        vs = lint_source(tmp_path, """
            from mxnet_tpu.parallel import dist

            class MyTrainer:
                def step(self, grads, big):
                    if dist.rank() % 2 == 0:
                        dist.allreduce_nd(grads)
                    else:
                        dist.allreduce_nd(grads)
            """, enable=["MX019"])
        assert vs == []

    def test_cold_scope_is_out_of_bounds(self, tmp_path):
        # not hot, not parallel/*, not reachable from a hot step:
        # mxrank must not flag offline tooling
        vs = lint_source(tmp_path, """
            from mxnet_tpu.parallel import dist

            def offline_report():
                if dist.rank() == 0:
                    dist.barrier("report")
            """, enable=["MX019"])
        assert vs == []

    def test_pragma_suppression(self, tmp_path):
        vs = lint_source(tmp_path, """
            from mxnet_tpu.parallel import dist

            class MyTrainer:
                def step(self, grads):
                    if dist.rank() == 0:  # mxlint: disable=MX019
                        dist.barrier("ckpt")
                    dist.allreduce_nd(grads)
            """, enable=["MX019"])
        assert vs == []


# ---------------------------------------------------------------------------
# MX020 — data-divergent collective schedule
# ---------------------------------------------------------------------------

class TestMX020:
    def test_flags_loss_gated_early_return_before_collective(
            self, tmp_path):
        vs = lint_source(tmp_path, """
            import math
            from mxnet_tpu.parallel import dist

            class MyTrainer:
                def step(self, loss, grads):
                    if math.isnan(loss):
                        return
                    dist.allreduce_nd(grads)
            """, enable=["MX020"])
        assert rules_hit(vs) == ["MX020"]

    def test_clean_allreduced_predicate_skip_step_idiom(self,
                                                        tmp_path):
        # the mxhealth skip_step pattern: the predicate itself is
        # all-reduced first, so every rank takes the same branch
        vs = lint_source(tmp_path, """
            import math
            from mxnet_tpu.parallel import dist

            class MyTrainer:
                def step(self, loss, grads):
                    bad = dist.allreduce_nd(math.isnan(loss))
                    if bad:
                        return
                    dist.allreduce_nd(grads)
            """, enable=["MX020"])
        assert vs == []

    def test_rank_taint_outranks_data_taint(self, tmp_path):
        # a predicate that is BOTH rank- and data-tainted is MX019
        vs = lint_source(tmp_path, """
            from mxnet_tpu.parallel import dist

            class MyTrainer:
                def step(self, loss, grads):
                    if dist.rank() == 0 and loss > 10.0:
                        return
                    dist.allreduce_nd(grads)
            """, enable=["MX019", "MX020"])
        assert rules_hit(vs) == ["MX019"]

    def test_clean_data_branch_without_collectives(self, tmp_path):
        vs = lint_source(tmp_path, """
            from mxnet_tpu.parallel import dist

            class MyTrainer:
                def step(self, loss, grads):
                    dist.allreduce_nd(grads)
                    if loss > 10.0:
                        self.overflow_count += 1
            """, enable=["MX020"])
        assert vs == []


# ---------------------------------------------------------------------------
# the taint lattice itself (fast unit surface)
# ---------------------------------------------------------------------------

class TestTaintLattice:
    def _mt(self, src):
        import ast

        from mxnet_tpu.analysis.mxrank import ModuleTaint

        return ModuleTaint(ast.parse(textwrap.dedent(src)))

    def test_rank_and_data_param_seeding(self):
        from mxnet_tpu.analysis.mxrank import DATA, RANK

        mt = self._mt("""
            def f(rank, loss):
                a = rank + 1
                b = loss * 2.0
                c = a if b else rank
                return c
            """)
        assert mt.return_taint("f") == (RANK | DATA)

    def test_collective_sanitizes(self):
        mt = self._mt("""
            def f(loss):
                import mxnet_tpu.parallel.dist as dist
                ok = dist.allreduce_nd(loss)
                return ok
            """)
        assert mt.return_taint("f") == 0

    def test_helper_summary_propagates_taint(self):
        from mxnet_tpu.analysis.mxrank import RANK

        mt = self._mt("""
            def who_am_i():
                import jax
                return jax.process_index()

            def f():
                return who_am_i() + 1
            """)
        assert mt.return_taint("f") == RANK

    def test_divergence_names_the_branch_multisets(self):
        mt = self._mt("""
            def step(rank):
                import mxnet_tpu.parallel.dist as dist
                if rank == 0:
                    dist.barrier("x")
                dist.allreduce_nd(1)
            """)
        funcs = {name: node for name, cls, node in mt.functions()}
        divs = mt.analyze("step", None, funcs["step"])
        assert len(divs) == 1
        msg = divs[0].describe()
        assert "barrier" in msg and "allreduce" in msg


# ---------------------------------------------------------------------------
# runtime ledger: fingerprint encode / compare / publish
# ---------------------------------------------------------------------------

@pytest.fixture
def sched(tmp_path, monkeypatch):
    from mxnet_tpu.parallel import schedule

    monkeypatch.setenv("MXNET_RANKCHECK", "1")
    schedule.reset()
    schedule.configure(str(tmp_path), 0)
    yield schedule
    schedule.reset()


class TestScheduleLedger:
    def test_record_assigns_dense_seq_and_sets_gauge(self, sched):
        assert sched.record("dist.allreduce", "allreduce",
                            "float32", 4096) == 0
        assert sched.record("dist.barrier", "barrier") == 1
        fp = sched.fingerprint()
        assert fp["seq"] == 2 and len(fp["window"]) == 2
        assert fp["window"][0] == ["dist.allreduce", "allreduce",
                                   "float32", 4096, 0]

    def test_window_is_bounded(self, tmp_path, monkeypatch):
        from mxnet_tpu.parallel import schedule

        monkeypatch.setenv("MXNET_RANKCHECK", "1")
        monkeypatch.setenv("MXNET_RANKCHECK_WINDOW", "8")
        schedule.reset()
        schedule.configure(str(tmp_path), 0)
        try:
            for i in range(50):
                schedule.record("s", "op", "", i)
            fp = schedule.fingerprint()
            assert fp["seq"] == 50 and len(fp["window"]) == 8
            assert fp["window"][0][4] == 42  # oldest retained seq
        finally:
            schedule.reset()

    def test_digest_is_content_addressed(self, sched):
        sched.record("s", "allreduce", "f32", 8)
        a = sched.fingerprint()["digest"]
        assert a == sched.fingerprint()["digest"]
        sched.record("s", "barrier", "", 0)
        assert sched.fingerprint()["digest"] != a

    def test_publish_read_peer_roundtrip(self, sched, tmp_path):
        sched.record("s", "allreduce", "f32", 8)
        assert sched.publish(force=True)
        fp = sched.read_peer(0, str(tmp_path))
        assert fp["seq"] == 1 and fp["rank"] == 0
        # unchanged seq -> publish skipped unless forced
        assert sched.publish() is False

    def test_compare_matching_and_behind_peer_are_none(self, sched):
        for _ in range(3):
            sched.record("s", "allreduce", "f32", 8)
        mine = sched.fingerprint()
        same = dict(mine, rank=1)
        assert sched.compare(mine, same) is None
        behind = {"rank": 1, "seq": 2,
                  "window": mine["window"][:2]}
        assert sched.compare(mine, behind) is None  # dead, not divergent

    def test_compare_finds_first_divergent_seq(self, sched):
        for _ in range(3):
            sched.record("dist.allreduce", "allreduce", "f32", 8)
        mine = sched.fingerprint()
        theirs = {"rank": 1, "seq": 3, "window": [
            ["dist.allreduce", "allreduce", "f32", 8, 0],
            ["dist.barrier", "barrier", "", 0, 1],
            ["dist.allreduce", "allreduce", "f32", 8, 2]]}
        div = sched.compare(mine, theirs)
        assert div["seq"] == 1 and div["peer"] == 1
        assert "barrier@1" in " ".join(div["theirs"])

    def test_off_switch_records_nothing(self, tmp_path, monkeypatch):
        from mxnet_tpu.parallel import schedule

        monkeypatch.setenv("MXNET_RANKCHECK", "0")
        schedule.reset()
        try:
            assert schedule.record("s", "op") == -1
            assert schedule.fingerprint()["seq"] == 0
            assert schedule.publish(force=True) is False
            assert schedule.divergence_details(wait_s=0.0) is None
        finally:
            schedule.reset()

    def test_ledger_off_overhead_gate(self, monkeypatch):
        """The tier-1 overhead gate: with MXNET_RANKCHECK=0 a record()
        is one resolved boolean check.  Bound it ABSOLUTELY at 2us per
        call (best of 5 trials): the cheapest real collective this
        guards is ~100us+ of dispatch, so 2us keeps the ledger-off tax
        well under the 3%% acceptance bar without a flaky A/B timing."""
        from mxnet_tpu.parallel import schedule

        monkeypatch.setenv("MXNET_RANKCHECK", "0")
        schedule.reset()
        try:
            schedule.record("warm", "up")  # resolve _ON once
            n = 100_000
            best = float("inf")
            for _ in range(5):
                t0 = time.perf_counter()
                for _ in range(n):
                    schedule.record("dist.allreduce", "allreduce",
                                    "float32", 4096)
                best = min(best, time.perf_counter() - t0)
            assert best / n < 2e-6, f"{best / n * 1e9:.0f}ns per call"
        finally:
            schedule.reset()


# ---------------------------------------------------------------------------
# the watchdog-timeout reclassification (single-process, fake peers)
# ---------------------------------------------------------------------------

class TestReclassification:
    def _fake_peer(self, tmp_path, window, seq=None):
        from mxnet_tpu.parallel import schedule

        fp = {"rank": 1, "seq": seq if seq is not None
              else (window[-1][4] + 1 if window else 0),
              "window": window, "digest": "peer"}
        with open(os.path.join(str(tmp_path),
                               schedule.stamp_name(1)), "w") as f:
            json.dump(fp, f)

    def test_timeout_with_divergent_peer_raises_divergence(
            self, sched, tmp_path, monkeypatch):
        from mxnet_tpu.parallel import dist
        from mxnet_tpu.resilience.elastic import ScheduleDivergence

        monkeypatch.setenv("MXNET_RANKCHECK_WAIT_S", "0.5")
        monkeypatch.setattr(dist, "_POISONED", None)
        sched.record("dist.allreduce", "allreduce", "f32", 8)
        self._fake_peer(tmp_path,
                        [["dist.barrier", "barrier", "", 0, 0]])
        with pytest.raises(ScheduleDivergence) as ei:
            dist._run_with_watchdog(lambda: time.sleep(5.0), 0.2,
                                    "allreduce")
        assert ei.value.seq == 0 and ei.value.peer == 1
        assert ei.value.transient is False
        assert "MX019" in str(ei.value)
        monkeypatch.setattr(dist, "_POISONED", None)

    def test_timeout_with_matching_peer_stays_peerfailed(
            self, sched, tmp_path, monkeypatch):
        from mxnet_tpu.parallel import dist
        from mxnet_tpu.resilience.elastic import PeerFailed

        monkeypatch.setenv("MXNET_RANKCHECK_WAIT_S", "0.2")
        monkeypatch.setattr(dist, "_POISONED", None)
        sched.record("dist.allreduce", "allreduce", "f32", 8)
        self._fake_peer(tmp_path,
                        [["dist.allreduce", "allreduce", "f32", 8, 0]])
        with pytest.raises(PeerFailed):
            dist._run_with_watchdog(lambda: time.sleep(5.0), 0.2,
                                    "allreduce")
        monkeypatch.setattr(dist, "_POISONED", None)

    def test_chaos_divergence_site_raises_on_single_process(
            self, sched, tmp_path):
        from mxnet_tpu.parallel import dist
        from mxnet_tpu.resilience import chaos
        from mxnet_tpu.resilience.elastic import ScheduleDivergence
        from mxnet_tpu.telemetry import instruments as _ins

        self._fake_peer(tmp_path,
                        [["dist.allreduce", "allreduce", "", 0, 0]])
        before = _ins.schedule_divergence_total("dist.allreduce").value
        with chaos.inject("dist.divergence", at=1):
            with pytest.raises(ScheduleDivergence) as ei:
                dist._guard_single("dist.allreduce")
        assert "!divergent" in " ".join(ei.value.mine)
        assert _ins.schedule_divergence_total(
            "dist.allreduce").value == before + 1
        # the next collective records clean again
        dist._guard_single("dist.allreduce")

    def test_heartbeat_piggyback_publishes_and_clear_removes(
            self, sched, tmp_path):
        from mxnet_tpu.resilience.heartbeat import (HeartbeatMonitor,
                                                    HeartbeatWriter)

        w = HeartbeatWriter(str(tmp_path), rank=0)
        sched.record("dist.allreduce", "allreduce", "f32", 8)
        w.beat(step=1)
        stamp = tmp_path / sched.stamp_name(0)
        assert stamp.exists()
        assert sched.read_peer(0, str(tmp_path))["seq"] == 1
        HeartbeatMonitor(str(tmp_path)).clear()
        assert not stamp.exists()  # new generation: no stale compares


# ---------------------------------------------------------------------------
# supervisor: a divergence exit is job-fatal with zero restarts
# ---------------------------------------------------------------------------

class TestSupervisorDivergence:
    def _sup(self, tmp_path, **kw):
        from mxnet_tpu.resilience import elastic

        return elastic.Supervisor(
            ["true"], world=2, directory=str(tmp_path),
            hb_timeout_s=1.0, grace_s=0.5, poll_s=0.05, **kw)

    def test_divergence_exit_aborts_without_restart(self, tmp_path,
                                                    monkeypatch):
        from mxnet_tpu.resilience.elastic import RC_DIVERGENCE
        from mxnet_tpu.telemetry import instruments as _ins

        sup = self._sup(tmp_path, max_restarts=3)
        spawned = []
        monkeypatch.setattr(sup, "_spawn",
                            lambda gen, n: (spawned.append(n), [])[1])
        monkeypatch.setattr(sup, "_watch", lambda *a, **kw: {
            "ok": False, "failed": [], "rcs": {0: RC_DIVERGENCE, 1: 44},
            "exits": {0: {"rc": RC_DIVERGENCE,
                          "classified": "divergence"},
                      1: {"rc": 44, "classified": "winddown"}},
            "t_detect": 0.0, "t_detect_unix": 0.0,
            "t_first_step": None, "tails": {}})
        before = _ins.elastic_restarts_total("aborted").value
        rep = sup.run()
        assert rep["ok"] is False
        assert rep["restarts"] == 0  # the budget was NOT consumed
        assert spawned == [2]        # and no second generation spawned
        assert "divergence" in rep["error"]
        epoch = rep["epochs"][0]
        assert epoch["schedule_divergence"] is True
        assert epoch["diverged_ranks"] == [0]
        assert _ins.elastic_restarts_total("aborted").value \
            == before + 1

    def test_exit_record_classifies_rc45_as_divergence(self):
        from mxnet_tpu.resilience import elastic

        class _P:
            returncode = elastic.RC_DIVERGENCE

            def poll(self):
                return self.returncode

        recs = elastic.Supervisor._exit_records(
            [{"rank": 0, "proc": _P()}], killed=[])
        assert recs["0"]["classified"] == "divergence"

    def test_budget_exhaustion_emits_aborted_counter(self, tmp_path,
                                                     monkeypatch):
        """Regression (satellite bugfix): the budget-exhausted
        job-dead path must count mode='aborted', not go unmetered."""
        from mxnet_tpu.telemetry import instruments as _ins

        sup = self._sup(tmp_path, max_restarts=0)
        monkeypatch.setattr(sup, "_spawn", lambda gen, n: [])
        monkeypatch.setattr(sup, "_watch", lambda *a, **kw: {
            "ok": False, "failed": [0], "rcs": {0: 1, 1: 44},
            "exits": {0: {"rc": 1, "classified": "died"},
                      1: {"rc": 44, "classified": "winddown"}},
            "t_detect": 0.0, "t_detect_unix": 0.0,
            "t_first_step": None, "tails": {}})
        before = _ins.elastic_restarts_total("aborted").value
        rep = sup.run()
        assert rep["ok"] is False and "budget" in rep["error"]
        assert _ins.elastic_restarts_total("aborted").value \
            == before + 1


# ---------------------------------------------------------------------------
# the real 2-process e2e (nightly mxrank stage)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_e2e_divergent_rank_is_classified_not_restarted(tmp_path):
    """THE ISSUE 20 known-answer, live: chaos makes rank 1 of a REAL
    2-process job enter a different collective at its 3rd site visit;
    the honest rank's watchdog fires, the schedule fingerprints
    disagree at one seq, BOTH ranks exit RC_DIVERGENCE (45), and the
    supervisor aborts the job with ZERO restarts consumed instead of
    burning the budget replaying a deterministic bug."""
    from mxnet_tpu.resilience.elastic import RC_DIVERGENCE

    out = str(tmp_path / "divergence.json")
    cmd = [sys.executable, os.path.join(_REPO, "tools",
                                        "elastic_run.py"),
           "--workers", "2", "--demo", "--cpu", "--mode", "replace",
           "--steps", "8", "--ckpt-every", "2", "--hb-timeout", "8",
           "--collective-timeout", "6", "--grace", "12", "--out", out,
           "--chaos", "dist.divergence@3:rank=1"]
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               MXNET_RANKCHECK_WAIT_S="6")
    env.pop("MXNET_CHAOS", None)
    env.pop("MXNET_CHAOS_SPEC", None)
    p = subprocess.run(cmd, capture_output=True, text=True,
                       timeout=420, env=env)
    assert p.returncode == 1, p.stdout[-3000:] + p.stderr[-2000:]
    with open(out) as f:
        rep = json.load(f)
    assert rep["ok"] is False
    assert rep["restarts"] == 0, rep
    assert "divergence" in rep["error"]
    epoch = rep["epochs"][0]
    assert epoch["schedule_divergence"] is True
    assert epoch["diverged_ranks"], epoch
    assert RC_DIVERGENCE in [int(v) for v in epoch["rcs"].values()]
